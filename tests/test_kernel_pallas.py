"""Pallas/Mosaic walk-kernel parity (ops/walk_pallas.py, the round-6
tentpole) — run in INTERPRET mode on CPU, so what is pinned here is the
PROGRAM (one-hot MXU gather, matrixized tally scatter with exact
collision peeling, VMEM-resident decoded table), not the Mosaic
lowering (scripts/probe_pallas_gather.py owns that question on
hardware).

Contracts:

  * BITWISE parity — kernel="pallas" reproduces the XLA walk
    bit-for-bit: flux, positions, elements, material ids, done flags,
    the track-length ledger, and the fused stats / integrity /
    convergence tails, at trace level (jittered meshes x dtypes x
    tally_scatter) and through the facade (io_pipeline x dtypes,
    multi-move chains).
  * TRANSFER invariant — the Mosaic kernel rides the packed staging
    program unchanged: a steady-state move is still exactly ONE H2D and
    ONE D2H.
  * RESOLVE-time policy — invalid combos (record_xpoints / checkify /
    megastep) fail at TallyConfig resolve, "auto" silently falls back
    to XLA outside the kernel's regime (no packed table, over the VMEM
    budget, non-TPU backend without the interpret opt-in), and the
    partitioned facade rejects an explicit "pallas" at construction.

Compile budget: tier-1 runs within a few seconds of its 870 s cap, so
the fast core suite (-m 'not slow') keeps only the resolve-time policy
tests (no compiles) plus ONE trace-level parity smoke; every test that
compiles a program is marked `slow` and runs in the dedicated
kernel-pallas CI step, which executes this file in full.
"""
from __future__ import annotations

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from pumiumtally_tpu import PumiTally, TallyConfig, make_flux
from pumiumtally_tpu.mesh.box import build_box_arrays
from pumiumtally_tpu.mesh.core import TetMesh
from pumiumtally_tpu.ops.walk import trace_impl
from pumiumtally_tpu.ops.walk_pallas import (
    kernel_vmem_bytes,
    select_backend,
    trace_pallas_impl,
)


def _jittered_mesh(nx, jitter, seed, dtype):
    coords, tets = build_box_arrays(1.0, 1.0, 1.0, nx, nx, nx)
    rng = np.random.default_rng(seed)
    h = 1.0 / nx
    interior = (
        (coords > 1e-9).all(axis=1) & (coords < 1 - 1e-9).all(axis=1)
    )
    coords = coords.copy()
    coords[interior] += rng.uniform(
        -jitter * h, jitter * h, (interior.sum(), 3)
    )
    cid = (coords[tets].mean(axis=1)[:, 0] > 0.5).astype(np.int32)
    return TetMesh.from_numpy(coords, tets, cid, dtype=dtype)


def _particles(mesh, dtype, n=80, seed=3, park_some=True):
    rng = np.random.default_rng(seed)
    elem = jnp.asarray(rng.integers(0, mesh.ntet, n).astype(np.int32))
    origin = jnp.asarray(
        np.asarray(mesh.centroids())[np.asarray(elem)], dtype
    )
    dest = jnp.asarray(rng.uniform(-0.1, 1.1, (n, 3)), dtype)
    fly = (
        jnp.asarray(rng.uniform(size=n) > 0.1)
        if park_some
        else jnp.ones(n, bool)
    )
    w = jnp.asarray(rng.uniform(0.5, 2.0, n), dtype)
    g = jnp.asarray(rng.integers(0, 2, n), jnp.int32)
    mat = jnp.full(n, -1, jnp.int32)
    return mesh, origin, dest, elem, fly, w, g, mat


def _assert_trace_bitwise(base, pal):
    for name in (
        "flux", "elem", "material_id", "done", "position",
        "track_length", "stats", "integrity", "convergence",
    ):
        a, b = getattr(base, name), getattr(pal, name)
        assert (a is None) == (b is None), name
        if a is not None:
            np.testing.assert_array_equal(
                np.asarray(b), np.asarray(a), err_msg=name
            )
    if base.conv_state is not None:
        for i, (a, b) in enumerate(zip(base.conv_state, pal.conv_state)):
            np.testing.assert_array_equal(
                np.asarray(b), np.asarray(a), err_msg=f"conv_state[{i}]"
            )
    assert int(pal.n_segments) == int(base.n_segments)
    assert int(pal.n_crossings) == int(base.n_crossings)


# --------------------------------------------------------------------- #
# Trace-level bitwise parity: jittered meshes x dtypes x tally_scatter
# --------------------------------------------------------------------- #
# Tier-1 budget: one (dtype, tally_scatter) combo stays in the fast
# core suite as the parity smoke; the rest of the grid is `slow` and
# runs in the dedicated kernel-pallas CI step (full file, no -m).
@pytest.mark.parametrize(
    "dtype",
    [jnp.float32, pytest.param(jnp.float64, marks=pytest.mark.slow)],
)
@pytest.mark.parametrize(
    "tally_scatter",
    ["pair", pytest.param("interleaved", marks=pytest.mark.slow)],
)
def test_trace_parity_jittered(dtype, tally_scatter):
    mesh = _jittered_mesh(4, 0.25, seed=11, dtype=dtype)
    args = _particles(mesh, dtype)
    kw = dict(
        initial=False, max_crossings=mesh.ntet + 8, tolerance=1e-8,
        n_groups=2, unroll=2, tally_scatter=tally_scatter,
    )
    base = trace_impl(*args, make_flux(mesh.ntet, 2, dtype, flat=True), **kw)
    pal = trace_impl(
        *args, make_flux(mesh.ntet, 2, dtype, flat=True),
        kernel="pallas", **kw,
    )
    assert bool(np.asarray(base.done).all())
    _assert_trace_bitwise(base, pal)


@pytest.mark.parametrize(
    "dtype",
    [
        pytest.param(jnp.float32, marks=pytest.mark.slow),
        pytest.param(jnp.float64, marks=pytest.mark.slow),
    ],
)
def test_trace_parity_feature_tails(dtype):
    """Stats + integrity + convergence tails fused on: every tail
    vector and the threaded batch accumulators are bitwise identical."""
    mesh = _jittered_mesh(4, 0.2, seed=5, dtype=dtype)
    args = _particles(mesh, dtype)
    nbins = mesh.ntet * 2

    def conv0():
        return (
            jnp.zeros(nbins, dtype), jnp.zeros(nbins, dtype),
            jnp.zeros((), jnp.int32), jnp.zeros((), jnp.int32),
        )

    kw = dict(
        initial=False, max_crossings=mesh.ntet + 8, tolerance=1e-8,
        n_groups=2, integrity=True, tally_scatter="pair",
    )
    base = trace_impl(
        *args, make_flux(mesh.ntet, 2, dtype, flat=True),
        conv_state=conv0(), **kw,
    )
    pal = trace_impl(
        *args, make_flux(mesh.ntet, 2, dtype, flat=True),
        conv_state=conv0(), kernel="pallas", **kw,
    )
    assert base.integrity is not None and base.convergence is not None
    _assert_trace_bitwise(base, pal)


@pytest.mark.slow
def test_trace_parity_initial_search(dtype=jnp.float64):
    """The tally-free location search: nothing scored, domain clips
    only — same contract through the kernel."""
    mesh = _jittered_mesh(4, 0.2, seed=9, dtype=dtype)
    args = _particles(mesh, dtype, park_some=False)
    kw = dict(
        initial=True, max_crossings=mesh.ntet + 8, tolerance=1e-8,
        n_groups=2,
    )
    base = trace_impl(*args, make_flux(mesh.ntet, 2, dtype, flat=True), **kw)
    pal = trace_impl(
        *args, make_flux(mesh.ntet, 2, dtype, flat=True),
        kernel="pallas", **kw,
    )
    _assert_trace_bitwise(base, pal)
    np.testing.assert_array_equal(
        np.asarray(pal.flux), 0.0
    )  # the search never scores


@pytest.mark.slow
def test_trace_parity_odd_lane_count(dtype=jnp.float32):
    """n not a multiple of the lane block: the pad lanes must be inert
    (parity + no phantom scores)."""
    mesh = _jittered_mesh(3, 0.2, seed=2, dtype=dtype)
    args = _particles(mesh, dtype, n=37)
    kw = dict(
        initial=False, max_crossings=mesh.ntet + 8, tolerance=1e-8,
        n_groups=2, tally_scatter="pair",
    )
    base = trace_impl(*args, make_flux(mesh.ntet, 2, dtype, flat=True), **kw)
    pal = trace_pallas_impl(
        *args, make_flux(mesh.ntet, 2, dtype, flat=True),
        lane_block=16, **kw,
    )
    _assert_trace_bitwise(base, pal)


# --------------------------------------------------------------------- #
# Facade parity: io_pipeline x dtype, multi-move chains
# --------------------------------------------------------------------- #
N = 96


@pytest.fixture(scope="module")
def mesh64():
    coords, t2v = build_box_arrays(1.0, 1.0, 1.0, 3, 3, 3)
    cen = coords[t2v].mean(axis=1)
    cls = np.where(cen[:, 0] < 0.5, 1, 2).astype(np.int32)
    return TetMesh.from_numpy(coords, t2v, class_id=cls, dtype=jnp.float64)


def _drive(t, moves=3, seed=17):
    rng = np.random.default_rng(seed)
    n = t.num_particles
    pos = rng.uniform(0.05, 0.95, (n, 3))
    t.initialize_particle_location(pos.ravel().copy(), n * 3)
    outs, prev = [], pos
    for _ in range(moves):
        dest = np.clip(prev + rng.normal(0, 0.25, (n, 3)), -0.1, 1.1)
        buf = dest.ravel().copy()
        flying = np.ones(n, np.int8)
        flying[::7] = 0
        w = rng.uniform(0.5, 2.0, n)
        g = rng.integers(0, 2, n).astype(np.int32)
        mats = np.full(n, 9, np.int32)
        t.move_to_next_location(buf, flying, w, g, mats, buf.size)
        outs.append((buf.reshape(n, 3).copy(), mats.copy()))
        prev = buf.reshape(n, 3).copy()
    return outs


def _cfg(io, dtype=jnp.float64, **kw):
    return TallyConfig(
        n_groups=2, dtype=dtype, tolerance=1e-8, io_pipeline=io, **kw
    )


@pytest.fixture(scope="module")
def golden_xla(mesh64):
    t = PumiTally(mesh64, N, _cfg("packed", kernel="xla"))
    outs = _drive(t)
    return outs, np.asarray(t.raw_flux), t.total_segments


@pytest.mark.slow
@pytest.mark.parametrize("io", ["legacy", "packed", "overlap"])
def test_facade_parity_io_modes(mesh64, golden_xla, io):
    outs_a, flux_a, segs_a = golden_xla
    b = PumiTally(mesh64, N, _cfg(io, kernel="pallas"))
    assert b._kernel == "pallas"
    outs_b = _drive(b)
    for (pa, ma), (pb, mb) in zip(outs_a, outs_b):
        np.testing.assert_array_equal(pb, pa)
        np.testing.assert_array_equal(mb, ma)
    np.testing.assert_array_equal(np.asarray(b.raw_flux), flux_a)
    assert b.total_segments == segs_a


@pytest.mark.slow
def test_facade_parity_f32(mesh64):
    coords, t2v = build_box_arrays(1.0, 1.0, 1.0, 3, 3, 3)
    cen = coords[t2v].mean(axis=1)
    cls = np.where(cen[:, 0] < 0.5, 1, 2).astype(np.int32)
    mesh = TetMesh.from_numpy(coords, t2v, class_id=cls, dtype=jnp.float32)
    a = PumiTally(mesh, N, _cfg("packed", jnp.float32, kernel="xla"))
    b = PumiTally(mesh, N, _cfg("packed", jnp.float32, kernel="pallas"))
    outs_a, outs_b = _drive(a, moves=2), _drive(b, moves=2)
    for (pa, ma), (pb, mb) in zip(outs_a, outs_b):
        np.testing.assert_array_equal(pb, pa)
        np.testing.assert_array_equal(mb, ma)
    np.testing.assert_array_equal(
        np.asarray(b.raw_flux), np.asarray(a.raw_flux)
    )


@pytest.mark.slow
def test_facade_parity_feature_tails_telemetry(mesh64):
    """Stats/integrity/convergence fused tails through the packed
    facade path: identical flux AND identical telemetry read surfaces."""
    kw = dict(
        integrity="warn", convergence=True, batch_moves=2,
        walk_stats=True,
    )
    a = PumiTally(mesh64, N, _cfg("packed", kernel="xla", **kw))
    b = PumiTally(mesh64, N, _cfg("packed", kernel="pallas", **kw))
    outs_a, outs_b = _drive(a), _drive(b)
    for (pa, ma), (pb, mb) in zip(outs_a, outs_b):
        np.testing.assert_array_equal(pb, pa)
        np.testing.assert_array_equal(mb, ma)
    np.testing.assert_array_equal(
        np.asarray(b.raw_flux), np.asarray(a.raw_flux)
    )
    ta, tb = a.telemetry(), b.telemetry()
    assert tb["totals"]["crossings"] == ta["totals"]["crossings"]
    assert tb["totals"]["segments"] == ta["totals"]["segments"]
    assert (
        tb["integrity"]["violations"] == ta["integrity"]["violations"]
    )
    np.testing.assert_array_equal(
        np.asarray(b.relative_error()), np.asarray(a.relative_error())
    )
    assert tb["convergence"]["n_batches"] == ta["convergence"]["n_batches"]
    assert tb["convergence"]["scored"] == ta["convergence"]["scored"]


@pytest.mark.slow
def test_steady_state_one_transfer_each_way_pallas(mesh64):
    """The Mosaic kernel rides the packed staging program unchanged:
    ONE H2D (the move record) + ONE D2H (the coalesced readback)."""
    t = PumiTally(mesh64, N, _cfg("packed", kernel="pallas"))
    _drive(t, moves=2)  # warm/compile
    totals = t.telemetry()["totals"]
    before = (totals["h2d_transfers"], totals["d2h_transfers"])
    rng = np.random.default_rng(5)
    buf = rng.uniform(0.1, 0.9, (N, 3)).ravel().copy()
    with jax.transfer_guard("disallow"):
        t.move_to_next_location(
            buf, np.ones(N, np.int8), np.ones(N),
            np.zeros(N, np.int32), np.full(N, -1, np.int32),
        )
    totals = t.telemetry()["totals"]
    assert totals["h2d_transfers"] - before[0] == 1
    assert totals["d2h_transfers"] - before[1] == 1


# --------------------------------------------------------------------- #
# Resolve-time policy: combos, env override, auto fallback
# --------------------------------------------------------------------- #
def test_resolve_kernel_rejects_record_xpoints():
    with pytest.raises(ValueError, match="intersection points"):
        TallyConfig(kernel="pallas", record_xpoints=4).resolve_kernel()


def test_resolve_kernel_rejects_checkify():
    with pytest.raises(ValueError, match="checkify"):
        TallyConfig(
            kernel="pallas", checkify_invariants=True
        ).resolve_kernel()


def test_resolve_kernel_rejects_megastep():
    with pytest.raises(ValueError, match="megastep"):
        TallyConfig(kernel="pallas", megastep=4).resolve_kernel()


def test_resolve_kernel_rejects_unknown():
    with pytest.raises(ValueError, match="kernel must be"):
        TallyConfig(kernel="mosaic").resolve_kernel()


def test_resolve_megastep_rejects_record_xpoints():
    with pytest.raises(ValueError, match="record_xpoints"):
        TallyConfig(megastep=4, record_xpoints=4).resolve_megastep()


def test_resolve_megastep_rejects_checkify():
    with pytest.raises(ValueError, match="checkify_invariants"):
        TallyConfig(
            megastep=2, checkify_invariants=True
        ).resolve_megastep()


def test_env_override_beats_field(monkeypatch):
    monkeypatch.setenv("PUMI_TPU_KERNEL", "pallas")
    assert TallyConfig(kernel="xla").resolve_kernel() == "pallas"
    monkeypatch.setenv("PUMI_TPU_KERNEL", "bogus")
    with pytest.raises(ValueError, match="kernel must be"):
        TallyConfig().resolve_kernel()


def test_env_pallas_over_debug_config_downgrades(monkeypatch):
    """An env-forced 'pallas' over a config carrying a debug surface
    downgrades to 'xla' (operational sweeps never break debug runs);
    the same conflict written INTO the config raises."""
    monkeypatch.setenv("PUMI_TPU_KERNEL", "pallas")
    assert (
        TallyConfig(record_xpoints=4).resolve_kernel() == "xla"
    )
    assert (
        TallyConfig(checkify_invariants=True).resolve_kernel() == "xla"
    )
    with pytest.raises(ValueError, match="intersection points"):
        TallyConfig(
            kernel="pallas", record_xpoints=4
        ).resolve_kernel()


def test_select_backend_auto_platform_gate(monkeypatch):
    """auto → pallas only on a real TPU (or with the interpret opt-in);
    the CPU test backend resolves to xla without the env."""
    monkeypatch.delenv("PUMI_TPU_PALLAS_INTERPRET", raising=False)
    kw = dict(
        ntet=200, n_particles=64, n_groups=2, dtype=jnp.float32,
        packed=True,
    )
    assert select_backend("auto", **kw) == "xla"
    monkeypatch.setenv("PUMI_TPU_PALLAS_INTERPRET", "1")
    assert select_backend("auto", **kw) == "pallas"
    assert select_backend("auto", platform="tpu", **kw) == "pallas"


def test_select_backend_auto_vmem_fallback(monkeypatch):
    """The acceptance contract: auto above the VMEM tile budget falls
    back to XLA without error; explicit pallas raises with the budget
    arithmetic in the message."""
    monkeypatch.setenv("PUMI_TPU_PALLAS_INTERPRET", "1")
    big = dict(
        ntet=4_000_000, n_particles=1024, n_groups=8,
        dtype=jnp.float32, packed=True,
    )
    assert kernel_vmem_bytes(4_000_000, 1024, 8, 4) > 8 * 2**20
    assert select_backend("auto", **big) == "xla"
    with pytest.raises(ValueError, match="VMEM working set"):
        select_backend("pallas", **big)


def test_select_backend_unpacked_mesh(monkeypatch):
    monkeypatch.setenv("PUMI_TPU_PALLAS_INTERPRET", "1")
    kw = dict(
        ntet=200, n_particles=64, n_groups=2, dtype=jnp.float32,
        packed=False,
    )
    assert select_backend("auto", **kw) == "xla"
    with pytest.raises(ValueError, match="geo20"):
        select_backend("pallas", **kw)


@pytest.mark.slow
def test_facade_auto_fallback_over_budget(mesh64, monkeypatch):
    """kernel='auto' on a facade whose workload exceeds the budget:
    constructs and moves on the XLA walk without error."""
    monkeypatch.setenv("PUMI_TPU_PALLAS_INTERPRET", "1")
    monkeypatch.setenv("PUMI_TPU_PALLAS_VMEM_MB", "0.001")
    t = PumiTally(mesh64, N, _cfg("packed", kernel="auto"))
    assert t._kernel == "xla"
    _drive(t, moves=1)
    monkeypatch.setenv("PUMI_TPU_PALLAS_VMEM_MB", "8")
    t2 = PumiTally(mesh64, N, _cfg("packed", kernel="auto"))
    assert t2._kernel == "pallas"


def test_facade_explicit_pallas_over_budget_raises(mesh64, monkeypatch):
    monkeypatch.setenv("PUMI_TPU_PALLAS_VMEM_MB", "0.001")
    with pytest.raises(ValueError, match="VMEM working set"):
        PumiTally(mesh64, N, _cfg("packed", kernel="pallas"))


def test_partitioned_rejects_explicit_pallas(mesh64):
    from pumiumtally_tpu.parallel.partitioned_api import PartitionedTally

    with pytest.raises(ValueError, match="single-chip"):
        PartitionedTally(
            mesh64, N, _cfg("packed", kernel="pallas"), n_parts=4
        )


def test_partitioned_auto_resolves_xla(mesh64):
    from pumiumtally_tpu.parallel.partitioned_api import PartitionedTally

    t = PartitionedTally(
        mesh64, N, _cfg("packed", kernel="auto"), n_parts=4
    )
    assert t._kernel == "xla"


@pytest.mark.slow
def test_run_source_moves_rejects_explicit_pallas(mesh64):
    t = PumiTally(mesh64, N, _cfg("packed", kernel="pallas"))
    rng = np.random.default_rng(0)
    t.initialize_particle_location(
        rng.uniform(0.1, 0.9, (N, 3)).ravel().copy()
    )
    with pytest.raises(NotImplementedError, match="pallas"):
        t.run_source_moves(1)


# --------------------------------------------------------------------- #
# Env-forced sweep (PUMI_TPU_KERNEL=pallas): graceful degradation
# --------------------------------------------------------------------- #
def test_select_backend_nonstrict_falls_back():
    """strict=False — the facades' spelling of 'this pallas came from
    the env sweep': outside the regime the resolve silently lands on
    XLA instead of raising."""
    kw = dict(n_particles=64, n_groups=2, dtype=jnp.float32)
    assert (
        select_backend("pallas", ntet=200, packed=False, strict=False, **kw)
        == "xla"
    )
    assert (
        select_backend(
            "pallas", ntet=4_000_000, packed=True, strict=False, **kw
        )
        == "xla"
    )
    assert (
        select_backend("pallas", ntet=200, packed=True, strict=False, **kw)
        == "pallas"
    )


def test_env_forced_pallas_in_regime_uses_kernel(mesh64, monkeypatch):
    monkeypatch.setenv("PUMI_TPU_KERNEL", "pallas")
    t = PumiTally(mesh64, N, _cfg("packed"))
    assert t._kernel == "pallas"


def test_env_forced_pallas_degrades_over_budget(mesh64, monkeypatch):
    """The same construction that raises for a config-explicit 'pallas'
    (test_facade_explicit_pallas_over_budget_raises) quietly runs the
    XLA walk when the 'pallas' came from the env sweep."""
    monkeypatch.setenv("PUMI_TPU_KERNEL", "pallas")
    monkeypatch.setenv("PUMI_TPU_PALLAS_VMEM_MB", "0.001")
    t = PumiTally(mesh64, N, _cfg("packed"))
    assert t._kernel == "xla"


def test_env_forced_pallas_degrades_partitioned(mesh64, monkeypatch):
    """PUMI_TPU_KERNEL=pallas over a partitioned suite (the CI faults
    sweep runs test_truncation.py, which builds PartitionedTally) must
    construct on the XLA step, not raise."""
    from pumiumtally_tpu.parallel.partitioned_api import PartitionedTally

    monkeypatch.setenv("PUMI_TPU_KERNEL", "pallas")
    t = PartitionedTally(mesh64, N, _cfg("packed"), n_parts=4)
    assert t._kernel == "xla"


@pytest.mark.slow
def test_env_forced_pallas_runs_megastep(mesh64, monkeypatch):
    """Device-sourced runs under the env sweep land on the XLA megastep
    silently; only a config-explicit kernel='pallas' rejects
    run_source_moves."""
    monkeypatch.setenv("PUMI_TPU_KERNEL", "pallas")
    t = PumiTally(mesh64, N, _cfg("packed"))
    rng = np.random.default_rng(0)
    t.initialize_particle_location(
        rng.uniform(0.1, 0.9, (N, 3)).ravel().copy()
    )
    out = t.run_source_moves(1)
    assert isinstance(out, dict)


@pytest.mark.slow
def test_truncation_escalation_composes(mesh64):
    """The resilience re-walk path drives the SAME kernel: a pallas
    facade with truncation_retries configured walks and re-walks
    bit-identically to the XLA one."""
    a = PumiTally(
        mesh64, N, _cfg("packed", kernel="xla", truncation_retries=2)
    )
    b = PumiTally(
        mesh64, N, _cfg("packed", kernel="pallas", truncation_retries=2)
    )
    outs_a, outs_b = _drive(a, moves=2), _drive(b, moves=2)
    for (pa, ma), (pb, mb) in zip(outs_a, outs_b):
        np.testing.assert_array_equal(pb, pa)
        np.testing.assert_array_equal(mb, ma)
    np.testing.assert_array_equal(
        np.asarray(b.raw_flux), np.asarray(a.raw_flux)
    )
