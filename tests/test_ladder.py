"""Executional ladder planner (utils/ladder.py).

Pins: (1) the simulator reproduces the round-4 hardware grid's RANKING
of schedules (the reconciliation VERDICT r4 weak #5 asked for — the
old slot model's dp_r250k pick measured 6.93 Mseg/s vs the dense
ladder's 7.60 because its round cost was 5x too cheap and its width
pinning excluded dense's shape); (2) the planner beats the dense
ladder under its own executional score and adapts to the mesh; (3)
planned schedules are valid and bit-identical in walk results (pure
scheduling)."""
import numpy as np
import pytest

import jax.numpy as jnp

from pumiumtally_tpu import build_box, make_flux, trace
from pumiumtally_tpu.ops.geometry import locate_points
from pumiumtally_tpu.ops.walk import normalize_compact_stages
from pumiumtally_tpu.utils.config import TallyConfig, dense_ladder
from pumiumtally_tpu.utils.ladder import (
    exp_survivors,
    plan_stages,
    simulate_ladder,
    survivors,
)

M = 1048576
# Round-4 hardware grid (bench_out/sweep_stages.out): name -> (schedule,
# measured ms/step). The simulator must reproduce the measured ordering
# of the three structurally distinct families.
GRID = {
    "default_r2": (((16, M // 2), (24, M // 4), (40, M // 8)), 3437.9),
    "dense": (
        ((8, 5 * M // 8), (16, 3 * M // 8), (24, M // 4), (32, M // 8),
         (48, M // 16), (64, M // 32), (96, M // 64)),
        2188.8,
    ),
    "dp_r250k": (
        ((16, M // 2), (24, M // 4), (40, M // 8), (48, M // 16),
         (56, M // 32), (76, 8192)),
        2400.1,
    ),
}
# Round-4 hardware fit (scripts/fit_ladder_model.py): ~81-85 ns/slot,
# ~110 ms/round. Only the RATIO matters for ranking.
ROUND_COST = 1.3 * M


def _score(stages, act):
    slots, rounds = simulate_ladder(act, M, stages, unroll=8)
    return slots + ROUND_COST * rounds


def test_simulator_reproduces_hardware_ranking():
    act = exp_survivors(M, 14.9)
    scores = {k: _score(v[0], act) for k, v in GRID.items()}
    meas = {k: v[1] for k, v in GRID.items()}
    assert (
        sorted(scores, key=scores.get) == sorted(meas, key=meas.get)
    ), (scores, meas)


def test_simulator_on_measured_counts_matches_analytic_family():
    # A synthetic exponential sample's survivors curve must score
    # schedules like the analytic curve of the same mean (shared
    # downstream path for measured decay inputs).
    rng = np.random.default_rng(0)
    counts = rng.exponential(14.9, 65536).astype(int)
    act_m = survivors(counts) * (M / 65536)
    act_a = exp_survivors(M, 14.9)
    for sched, _ in GRID.values():
        sm = _score(sched, act_m)
        sa = _score(sched, act_a)
        assert abs(sm - sa) / sa < 0.15, (sched, sm, sa)


def test_planner_beats_dense_under_executional_score():
    act = exp_survivors(M, 14.9)
    planned = plan_stages(M, 14.9)
    assert planned, "planner must produce a ladder at bench stats"
    assert _score(planned, act) <= _score(dense_ladder(M), act)


def test_planner_adapts_to_mesh_density():
    bench = plan_stages(M, 14.9)
    coarse = plan_stages(65536, 3.3)  # config-1 10k-tet profile
    denser = plan_stages(M, 32.6)  # 119-cell 10M-tet profile
    assert coarse, "short walks still get a (short) ladder"
    # Shorter walks end their ladder earlier; denser meshes stretch it.
    assert coarse[-1][0] < bench[-1][0] < denser[-1][0]
    # Schedules are valid by the walk's own rules.
    for s in (bench, coarse, denser):
        normalize_compact_stages(s, None, None, M, M // 8)


def test_config_plan_mode_resolves_and_scales():
    cfg = TallyConfig(compact_stages="plan")
    sched = cfg.resolve_compact_stages(M, ntet=998250)
    assert sched and all(len(s) >= 2 for s in sched)
    # Denser mesh -> later final boundary, same as the bench scaling.
    sched10m = cfg.resolve_compact_stages(M, ntet=10_110_954)
    assert sched10m[-1][0] > sched[-1][0]
    # "auto" stays the measured-best dense ladder, starts
    # density-scaled ((ntet/998250)^(1/3) — bench.py's cells/55).
    auto = TallyConfig(compact_stages="auto")
    a1 = auto.resolve_compact_stages(M, ntet=998250)
    assert a1 == dense_ladder(M)
    a10 = auto.resolve_compact_stages(M, ntet=10_110_954)
    assert a10[0][0] > a1[0][0]
    assert [w for _, w in a10] == [w for _, w in a1]


def test_planned_schedule_walk_is_bit_identical():
    mesh = build_box(1.0, 1.0, 1.0, 6, 6, 6, dtype=jnp.float32)
    n = 2048
    rng = np.random.default_rng(3)
    origin = jnp.asarray(rng.uniform(0.05, 0.95, (n, 3)), jnp.float32)
    elem = locate_points(mesh, origin, 1e-12)
    dest = jnp.asarray(
        np.clip(
            np.asarray(origin) + rng.normal(0, 0.2, (n, 3)), 0.01, 0.99
        ),
        jnp.float32,
    )
    args = (
        mesh, origin, dest, elem, jnp.ones(n, bool),
        jnp.ones(n, jnp.float32), jnp.zeros(n, jnp.int32),
        jnp.full(n, -1, jnp.int32),
    )
    kw = dict(initial=False, max_crossings=512, tolerance=1e-6)
    flat = trace(*args, make_flux(mesh.ntet, 1, jnp.float32), **kw)
    sched = plan_stages(n, 5.0)
    assert sched, "planner should ladder a 2048-lane batch"
    ladd = trace(
        *args, make_flux(mesh.ntet, 1, jnp.float32),
        compact_stages=sched, **kw,
    )
    np.testing.assert_array_equal(
        np.asarray(flat.position), np.asarray(ladd.position)
    )
    np.testing.assert_allclose(
        np.asarray(flat.flux), np.asarray(ladd.flux), rtol=0, atol=1e-5
    )
    assert int(flat.n_segments) == int(ladd.n_segments)


def test_adaptive_mode_replans_from_measured_crossings():
    """compact_stages='adaptive' re-plans after the first move from the
    measured crossings/move; results match 'plan' up to fp summation
    order (schedules group the scatter adds differently)."""
    from pumiumtally_tpu.api import PumiTally, TallyConfig

    mesh = build_box(1.0, 1.0, 1.0, 6, 6, 6, dtype=jnp.float64)
    cents = np.asarray(mesh.centroids())
    N = 2048

    def drive(mode, moves=2):
        t = PumiTally(
            mesh, N,
            TallyConfig(dtype=jnp.float64, n_groups=2,
                        compact_stages=mode),
        )
        rng = np.random.default_rng(4)
        elem = rng.integers(0, mesh.ntet, N).astype(np.int32)
        pos = cents[elem].astype(np.float64)
        t.initialize_particle_location(pos.reshape(-1).copy())
        prev = pos.copy()
        for _ in range(moves):
            d = rng.normal(0, 1, (N, 3))
            d /= np.linalg.norm(d, axis=1, keepdims=True)
            # LONG moves: the density estimate (mesh-only) cannot see
            # this — the measured mean crossings is far higher.
            ln = rng.exponential(0.8, (N, 1))
            buf = np.clip(prev + d * ln, 0.01, 0.99).reshape(-1).copy()
            t.move_to_next_location(
                buf, np.ones(N, np.int8), np.ones(N),
                np.zeros(N, np.int32), np.full(N, -1, np.int32),
            )
            prev = buf.reshape(N, 3)
        return t

    t_plan = drive("plan")
    t_adapt = drive("adaptive")
    # Identical physics regardless of schedule (flux to f64 rounding:
    # different schedules group the scatter adds differently, so the
    # accumulation ORDER differs — observed max 1.8e-15).
    np.testing.assert_allclose(
        np.asarray(t_adapt.raw_flux), np.asarray(t_plan.raw_flux),
        rtol=0, atol=1e-12,
    )
    # The adaptive schedule reflects the measured (long-move) profile:
    # it must differ from the density-only plan and end LATER (more
    # crossings/move -> later final boundary).
    assert t_adapt._replanned
    sched_a = t_adapt._compact_stages
    sched_p = t_plan._compact_stages
    assert sched_a != sched_p
    assert sched_a is None or sched_p is None or (
        sched_a[-1][0] > sched_p[-1][0]
    )


def test_adaptive_mode_rejected_where_it_cannot_replan():
    from pumiumtally_tpu.models.pipeline import StreamingTallyPipeline
    from pumiumtally_tpu.parallel.partitioned_api import PartitionedTally

    mesh = build_box(1.0, 1.0, 1.0, 3, 3, 3, dtype=jnp.float64)
    cfg = TallyConfig(dtype=jnp.float64, compact_stages="adaptive")
    with pytest.raises(NotImplementedError, match="adaptive"):
        PartitionedTally(mesh, 64, cfg, n_parts=8)
    with pytest.raises(NotImplementedError, match="adaptive"):
        StreamingTallyPipeline(mesh, config=cfg)
