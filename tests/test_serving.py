"""Tally-as-a-service contracts (pumiumtally_tpu/serving/, the
ROADMAP item-3 tentpole).

Contracts pinned here:

  * AOT PARITY — flux served through the program bank's deserialized
    executables is BITWISE identical to the jit path, per shape class,
    through the full facade loop (init search + megastep quanta).
  * WARM START — a FRESH PROCESS over a populated bank serves a
    multi-job workload with zero bank misses, zero bank compile
    seconds, and zero XLA compiles of the walk/megastep program
    families (pinned by the jax compile log), with results bitwise
    equal to the populating process.
  * DONATION RE-VALIDATION — a bank entry whose executable lost its
    donation (the PUMI_TPU_AOT_FAULT=drop_donation injection) is
    caught by the load-time validator with the named
    ``cost.donation.aot`` finding, recompiled, and rewritten; the
    rewritten entry loads clean.  The same validator is graft-check
    layer 3's ``cost.donation.aot`` gate (costmodel.check_aot), which
    must be clean on the real program families.
  * SCHEDULER — shape-bucketed admission is round-robin across
    classes, resident jobs time-slice at megastep-quantum granularity
    (fairness pinned on the quantum flight records), converged jobs
    evict early, and checkpoint preemption + restore replays
    BITWISE-identically to an uninterrupted run.
  * OBSERVABILITY — pumi_jobs_total{outcome} / pumi_queue_depth /
    pumi_aot_* / pumi_compile_seconds_total land in the scheduler
    registry and render as Prometheus text; per-job and per-quantum
    flight records exist.
  * PIPELINE ATTRIBUTION — StreamingTallyPipeline.BatchResult carries
    the per-submit resolved shape-class key.

Compile budget: the fast core (-m 'not slow') keeps the keying /
validator / request-validation tests (toy-program compiles only);
everything that compiles the real walk/megastep programs or launches
subprocesses is marked slow and runs in the dedicated CI serving step.
"""
from __future__ import annotations

import hashlib
import json
import os
import subprocess
import sys

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from pumiumtally_tpu import PumiTally, TallyConfig, build_box
from pumiumtally_tpu.ops.source import SourceParams
from pumiumtally_tpu.serving import (
    JobRequest,
    ProgramBank,
    TallyScheduler,
    run_saturation,
    synthetic_requests,
    validate_loaded,
)
from pumiumtally_tpu.serving import bank as bank_mod
from pumiumtally_tpu.tuning.shapes import bucket, classify

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_env(monkeypatch):
    """Serving contracts assume the knobs resolve from configs, not
    from a CI sweep's env overrides (quantum alignment and the AOT
    fault hook are what the tests drive explicitly)."""
    for var in (
        "PUMI_TPU_MEGASTEP", "PUMI_TPU_KERNEL", "PUMI_TPU_IO_PIPELINE",
        "PUMI_TPU_TUNING", "PUMI_TPU_AOT_FAULT", "PUMI_TPU_PROM_PORT",
    ):
        monkeypatch.delenv(var, raising=False)


@pytest.fixture(scope="module")
def mesh():
    return build_box(1.0, 1.0, 1.0, 2, 2, 2)


def _cfg(**kw):
    return TallyConfig(tolerance=1e-6, **kw)


def _run_facade(mesh, n, cfg, seed=7, moves=6, bank=None):
    """One facade run: init at repeated centroids + device-sourced
    moves; returns the raw flux bytes + physics totals."""
    t = PumiTally(mesh, n, cfg, program_bank=bank)
    cents = np.asarray(mesh.centroids(), np.float64)
    origins = cents[np.arange(n) % mesh.ntet].reshape(-1).copy()
    t.initialize_particle_location(origins)
    totals = t.run_source_moves(
        moves, SourceParams(seed=seed),
        weights=np.ones(n), groups=np.zeros(n, np.int32),
        alive=np.ones(n, bool),
    )
    return np.asarray(t.flux).copy(), totals


def _solo_reference(mesh, request, quantum, cfg):
    """The uninterrupted jit-path run of one scheduler job, padded to
    the same shape bucket with the same chunking (megastep=quantum),
    which the scheduler's interleaved/preempted execution must match
    bitwise."""
    import dataclasses

    origins = np.asarray(request.origins, np.float64).reshape(-1, 3)
    n = origins.shape[0]
    N = bucket(n)
    pad = np.broadcast_to(origins[0], (N - n, 3))
    origins_p = np.concatenate([origins, pad], axis=0)
    t = PumiTally(
        mesh, N, dataclasses.replace(cfg, megastep=quantum)
    )
    t.initialize_particle_location(origins_p.reshape(-1).copy())
    t.run_source_moves(
        request.n_moves, request.source,
        weights=np.concatenate([np.ones(n), np.zeros(N - n)]),
        groups=np.zeros(N, np.int32),
        alive=np.concatenate([np.ones(n, bool), np.zeros(N - n, bool)]),
    )
    return t.raw_flux.copy()


# --------------------------------------------------------------------- #
# Fast core: keying, the load-time validator, request validation
# --------------------------------------------------------------------- #
def test_entry_key_is_deterministic_and_statics_sensitive():
    args = (jnp.ones((8, 3), jnp.float32), jnp.zeros(8, jnp.int32))
    dyn = {"weight": jnp.ones(8, jnp.float32)}
    statics = {"n_moves": 4, "tolerance": 1e-6}
    k1 = bank_mod.entry_key("megastep", args, dyn, statics)
    k2 = bank_mod.entry_key("megastep", args, dyn, statics)
    assert k1 == k2 and k1.startswith("megastep-")
    # A static flip, a shape flip, and a dtype flip each re-key.
    assert k1 != bank_mod.entry_key(
        "megastep", args, dyn, {**statics, "n_moves": 8}
    )
    assert k1 != bank_mod.entry_key(
        "megastep", (jnp.ones((16, 3), jnp.float32), args[1]), dyn,
        statics,
    )
    assert k1 != bank_mod.entry_key(
        "megastep", (args[0].astype(jnp.float64), args[1]), dyn, statics
    )


def test_bank_section_is_environment_keyed(tmp_path):
    b = ProgramBank(str(tmp_path))
    assert b.section == bank_mod.section_key()
    assert b.section_dir == os.path.join(str(tmp_path), b.section)
    assert b.entries_on_disk() == []


def test_validate_loaded_toy_programs():
    """The validator's verdicts on executables whose donation state is
    known by construction: a donated toy round-trips clean, an
    undonated twin is the named cost.donation.aot finding."""
    from jax.experimental.serialize_executable import (
        deserialize_and_load,
        serialize,
    )

    from pumiumtally_tpu.analysis.costmodel import fresh_compile

    def f(x, y):
        return x * 2 + y, x.sum()

    x, y = jnp.ones(256), jnp.ones(256)

    def roundtrip(jitted):
        # fresh_compile: a toy compile served from the test session's
        # persistent compile cache does not serialize cleanly — the
        # exact cache interference the bank's compile path bypasses.
        comp = fresh_compile(jitted.trace(x, y).lower())
        payload, in_tree, out_tree = serialize(comp)
        return deserialize_and_load(payload, in_tree, out_tree)

    donated = roundtrip(jax.jit(f, donate_argnames=("x",)))
    assert validate_loaded(donated, "toy") == []
    undonated = roundtrip(jax.jit(f))
    problems = validate_loaded(undonated, "toy")
    assert [s for s, _ in problems] == ["cost.donation.aot"]
    # PARTIAL drops: the loaded plan must match the recorded fresh-
    # compile count exactly, not merely be non-empty.
    from pumiumtally_tpu.serving.bank import alias_marks

    n = alias_marks(donated)
    assert n >= 1
    assert validate_loaded(donated, "toy", expect_alias=n) == []
    partial = validate_loaded(donated, "toy", expect_alias=n + 1)
    assert [s for s, _ in partial] == ["cost.donation.aot"]
    assert "PARTIAL" in partial[0][1]


def test_scheduler_request_validation(mesh, tmp_path):
    sched = TallyScheduler(mesh, _cfg(), max_resident=1)
    with pytest.raises(ValueError, match="at least one particle"):
        sched.submit(JobRequest(origins=np.zeros((0, 3)), n_moves=4))
    with pytest.raises(ValueError, match="n_moves"):
        sched.submit(
            JobRequest(origins=np.zeros((4, 3)), n_moves=0)
        )
    sched.submit(
        JobRequest(origins=np.zeros((4, 3)), n_moves=1, job_id="a")
    )
    with pytest.raises(ValueError, match="duplicate"):
        sched.submit(
            JobRequest(origins=np.zeros((4, 3)), n_moves=1, job_id="a")
        )
    # Mis-sized per-lane arrays are rejected, never silently truncated
    # (a [:n] slice would scale the flux by the wrong source weights).
    with pytest.raises(ValueError, match="weights has 8"):
        sched.submit(JobRequest(
            origins=np.zeros((4, 3)), n_moves=1, weights=np.ones(8),
        ))
    with pytest.raises(ValueError, match="groups has 2"):
        sched.submit(JobRequest(
            origins=np.zeros((4, 3)), n_moves=1,
            groups=np.zeros(2, np.int32),
        ))
    with pytest.raises(ValueError, match="checkpoint_dir"):
        TallyScheduler(mesh, _cfg(), preempt_after=1)
    sched.close()


def test_job_padding_lands_on_the_tuning_ladder(mesh):
    sched = TallyScheduler(mesh, _cfg())
    jid = sched.submit(
        JobRequest(origins=np.full((40, 3), 0.5), n_moves=2)
    )
    job = sched.job(jid)
    assert job.padded_n == bucket(40) == 64
    assert job.shape_key == classify(
        mesh.ntet, 64, 2, jnp.float32,
        getattr(mesh, "geo20", None) is not None,
    ).key()
    sched.close()


# --------------------------------------------------------------------- #
# AOT parity + warm start
# --------------------------------------------------------------------- #
@pytest.mark.slow
def test_bank_facade_bitwise_and_warm_hits(mesh, tmp_path):
    cfg = _cfg(megastep=2)
    f_jit, tot_jit = _run_facade(mesh, 64, cfg)
    cold = ProgramBank(str(tmp_path))
    f_cold, tot_cold = _run_facade(mesh, 64, cfg, bank=cold)
    assert f_cold.tobytes() == f_jit.tobytes()
    assert tot_cold == tot_jit
    # First process: both families compiled + serialized.
    assert cold.misses == 2 and cold.hits == 0
    assert cold.compile_seconds > 0
    assert sorted(e.split("-")[0] for e in cold.entries_on_disk()) == [
        "megastep", "trace_packed",
    ]
    # A fresh bank over the same directory deserializes everything:
    # zero compiles, bitwise-identical service.
    warm = ProgramBank(str(tmp_path))
    f_warm, _ = _run_facade(mesh, 64, cfg, bank=warm)
    assert f_warm.tobytes() == f_jit.tobytes()
    assert warm.hits == 2 and warm.misses == 0 and warm.rewrites == 0
    assert warm.compile_seconds == 0.0


@pytest.mark.slow
def test_aot_flux_bitwise_per_shape_class(mesh, tmp_path):
    """Scheduler-served (AOT) flux == solo jit-path flux, bitwise, for
    every job across two shape classes."""
    cfg = _cfg()
    out = run_saturation(
        mesh, cfg, bank=ProgramBank(str(tmp_path)), n_jobs=4,
        class_sizes=(40, 100), n_moves=6, seed=3, max_resident=2,
        quantum_moves=2,
    )
    reqs = synthetic_requests(
        mesh, 4, class_sizes=(40, 100), n_moves=6, seed=3
    )
    keys = set()
    for req, row in zip(reqs, out["per_job"]):
        ref = _solo_reference(mesh, req, 2, cfg)
        got = out["results"][row["job"]]
        assert got.tobytes() == ref.tobytes(), row
        keys.add(row["shape_key"])
    assert len(keys) == 2  # two distinct shape buckets were served


_WARM_SCRIPT = """
import os, sys, json, hashlib, logging
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    )
msgs = []
class _H(logging.Handler):
    def emit(self, rec):
        msgs.append(rec.getMessage())
logging.getLogger().addHandler(_H())
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)
jax.config.update("jax_log_compiles", True)
sys.path.insert(0, {root!r})
import numpy as np
from pumiumtally_tpu import TallyConfig, build_box
from pumiumtally_tpu.serving import ProgramBank, run_saturation
mesh = build_box(1.0, 1.0, 1.0, 2, 2, 2)
bank = ProgramBank({bank!r})
out = run_saturation(
    mesh, TallyConfig(tolerance=1e-6), bank=bank, n_jobs=4,
    class_sizes=(40, 100), n_moves=4, seed=5, max_resident=2,
    quantum_moves=2,
)
hashes = {{
    k: hashlib.sha256(v.tobytes()).hexdigest()
    for k, v in sorted(out["results"].items())
}}
# "Finished XLA compilation of ..." is the BACKEND compile log; the
# "Compiling <name> with global shapes" line fires at lowering time,
# which the bank's load-time staleness probe performs on purpose
# (pure trace+lower, no backend compile).
family_compiles = [
    m for m in msgs
    if "Finished XLA compilation" in m
    and ("trace_packed" in m or "megastep" in m)
]
print(json.dumps({{
    "stats": bank.stats(),
    "hashes": hashes,
    "family_compiles": family_compiles,
    "outcomes": out["scheduler"]["outcomes"],
}}))
"""


@pytest.mark.slow
def test_warm_subprocess_serves_with_zero_compiles(mesh, tmp_path):
    """The acceptance pin: a FRESH server process over a populated
    bank runs the multi-job workload with zero bank misses, zero bank
    compile seconds, no XLA compile of either program family (compile
    log), and bitwise-identical results."""
    bank_dir = str(tmp_path / "bank")
    # Populate in-process (the "first server process").
    out = run_saturation(
        mesh, _cfg(), bank=ProgramBank(bank_dir), n_jobs=4,
        class_sizes=(40, 100), n_moves=4, seed=5, max_resident=2,
        quantum_moves=2,
    )
    want = {
        k: hashlib.sha256(v.tobytes()).hexdigest()
        for k, v in sorted(out["results"].items())
    }
    env = {
        k: v for k, v in os.environ.items()
        if not k.startswith("PUMI_TPU_")
        and k not in ("JAX_COMPILATION_CACHE_DIR",)
    }
    proc = subprocess.run(
        [sys.executable, "-c",
         _WARM_SCRIPT.format(root=ROOT, bank=bank_dir)],
        capture_output=True, text=True, timeout=600, env=env, cwd=ROOT,
    )
    assert proc.returncode == 0, proc.stderr
    got = json.loads(proc.stdout.strip().splitlines()[-1])
    assert got["stats"]["misses"] == 0, got["stats"]
    assert got["stats"]["rewrites"] == 0, got["stats"]
    assert got["stats"]["hits"] == 4, got["stats"]
    assert got["stats"]["compile_seconds"] == 0.0, got["stats"]
    assert got["family_compiles"] == [], got["family_compiles"]
    assert got["hashes"] == want
    assert got["outcomes"] == {"completed": 4}


# --------------------------------------------------------------------- #
# Donation re-validation (the PR 9 finding, closed)
# --------------------------------------------------------------------- #
@pytest.mark.slow
def test_donation_drop_is_caught_recompiled_and_rewritten(
    mesh, tmp_path, monkeypatch
):
    cfg = _cfg(megastep=2)
    f_jit, _ = _run_facade(mesh, 64, cfg)
    # Poison the bank: entries compiled WITHOUT donated arguments —
    # the executable on disk genuinely lost its aliasing plan.
    monkeypatch.setenv(bank_mod.ENV_FAULT, "drop_donation")
    poisoned = ProgramBank(str(tmp_path))
    f_poisoned, _ = _run_facade(mesh, 64, cfg, bank=poisoned)
    monkeypatch.delenv(bank_mod.ENV_FAULT)
    # Donation is an optimization: outputs stay correct either way.
    assert f_poisoned.tobytes() == f_jit.tobytes()
    assert poisoned.misses == 2 and poisoned.rewrites == 0
    # The load-time validator: both entries named, recompiled,
    # rewritten — and service continues bitwise.
    validator = ProgramBank(str(tmp_path))
    f_fixed, _ = _run_facade(mesh, 64, cfg, bank=validator)
    assert f_fixed.tobytes() == f_jit.tobytes()
    assert validator.rewrites == 2 and validator.hits == 0
    symbols = [f.symbol for f in validator.findings]
    assert symbols == ["cost.donation.aot", "cost.donation.aot"]
    assert {"megastep", "trace_packed"} == {
        f.message.split("]")[0].lstrip("[") for f in validator.findings
    }
    # The rewritten entries are clean: a third process is pure hits.
    clean = ProgramBank(str(tmp_path))
    f_clean, _ = _run_facade(mesh, 64, cfg, bank=clean)
    assert f_clean.tobytes() == f_jit.tobytes()
    assert clean.hits == 2 and clean.rewrites == 0
    assert clean.findings == []


@pytest.mark.slow
def test_cost_donation_aot_gate_is_clean():
    """Graft-check layer 3's AOT gate over the real base-rung
    programs: serialize -> deserialize keeps the donation + 1+1
    contract (the resolution of the analysis/costmodel.py:145
    finding)."""
    from pumiumtally_tpu.analysis import costmodel as M

    assert M.check_aot() == []


# --------------------------------------------------------------------- #
# Scheduler: fairness, eviction, preemption
# --------------------------------------------------------------------- #
@pytest.mark.slow
def test_scheduler_round_robin_fairness(mesh, tmp_path):
    """Admission rotates across shape classes and resident jobs each
    get exactly one quantum per round."""
    cfg = _cfg()
    sched = TallyScheduler(
        mesh, cfg, bank=ProgramBank(str(tmp_path)), max_resident=2,
        quantum_moves=2,
    )
    cents = np.asarray(mesh.centroids(), np.float64)
    ids = []
    for i, n in enumerate((40, 40, 100)):  # two of class A, one of B
        ids.append(sched.submit(JobRequest(
            origins=np.broadcast_to(cents[0], (n, 3)),
            n_moves=6, source=SourceParams(seed=100 + i),
            job_id=f"j{i}",
        )))
    sched.run()
    sched.close()
    admitted = [
        r["job"] for r in sched.recorder.records()
        if r["kind"] == "job_admitted"
    ]
    # Class round-robin: the first two admissions are DIFFERENT shape
    # classes (j0 from the 64-bucket, then j2 from the 128-bucket),
    # not the two same-class jobs in submit order.
    assert admitted[0] == "j0" and admitted[1] == "j2"
    quanta = [
        r["job"] for r in sched.recorder.records()
        if r["kind"] == "quantum"
    ]
    # While both slots were full, rounds alternate strictly.
    assert quanta[0:2] == ["j0", "j2"] and quanta[2:4] == ["j0", "j2"]
    assert all(sched.job(i).outcome == "completed" for i in ids)
    # Fairness never broke bitwise parity with solo runs.
    for i, jid in enumerate(ids):
        n = (40, 40, 100)[i]
        req = JobRequest(
            origins=np.broadcast_to(cents[0], (n, 3)), n_moves=6,
            source=SourceParams(seed=100 + i),
        )
        assert sched.result(jid).tobytes() == _solo_reference(
            mesh, req, 2, cfg
        ).tobytes()


@pytest.mark.slow
def test_preemption_resume_is_bitwise_replay(mesh, tmp_path):
    """A checkpoint-preempted job restores and finishes bitwise equal
    to an uninterrupted run (the PR 2 subsystem as the preemption
    mechanism)."""
    cfg = _cfg()
    ck = tmp_path / "ck"
    ck.mkdir()
    sched = TallyScheduler(
        mesh, cfg, bank=ProgramBank(str(tmp_path / "bank")),
        max_resident=1, quantum_moves=2, preempt_after=1,
        checkpoint_dir=str(ck),
    )
    reqs = synthetic_requests(
        mesh, 2, class_sizes=(40,), n_moves=8, seed=11
    )
    ids = [sched.submit(r) for r in reqs]
    sched.run()
    sched.close()
    preempted = [j for j in sched.jobs() if j.preemptions > 0]
    assert preempted, "preemption never fired"
    stats = sched.stats()
    assert stats["preemptions"] >= 1
    for req, jid in zip(reqs, ids):
        job = sched.job(jid)
        assert job.outcome == "completed"
        assert job.checkpoint is None  # cleaned up after completion
        assert sched.result(jid).tobytes() == _solo_reference(
            mesh, req, 2, cfg
        ).tobytes()


@pytest.mark.slow
def test_converged_job_evicts_early(mesh, tmp_path):
    """With convergence observability on, a job that reaches its
    precision target is evicted before its move budget runs out."""
    cfg = _cfg(
        convergence=True, rel_err_target=1e6, converged_fraction=0.1,
    )
    sched = TallyScheduler(
        mesh, cfg, bank=None, max_resident=1, quantum_moves=2,
    )
    req = synthetic_requests(
        mesh, 1, class_sizes=(40,), n_moves=30, seed=2
    )[0]
    jid = sched.submit(req)
    sched.run()
    sched.close()
    job = sched.job(jid)
    assert job.outcome == "converged"
    assert job.moves_done < 30
    assert sched.stats()["outcomes"] == {"converged": 1}


@pytest.mark.slow
def test_serving_metrics_and_prometheus_render(mesh, tmp_path):
    out = run_saturation(
        mesh, _cfg(), bank=ProgramBank(str(tmp_path)), n_jobs=2,
        class_sizes=(40,), n_moves=4, seed=9, max_resident=2,
        quantum_moves=2,
    )
    assert out["jobs_per_sec"] > 0
    sched_stats = out["scheduler"]
    assert sched_stats["outcomes"].get("completed") == 2
    aot = sched_stats["aot"]
    assert aot["misses"] == 2 and aot["compile_seconds"] > 0
    # The bank shares the scheduler registry when constructed from a
    # path — exercise that wiring + the Prometheus text surface.
    sched = TallyScheduler(
        mesh, _cfg(), bank=str(tmp_path), max_resident=1,
        quantum_moves=2,
    )
    jid = sched.submit(
        JobRequest(
            origins=np.full((40, 3), 0.5), n_moves=2,
            source=SourceParams(seed=1),
        )
    )
    sched.run()
    text = sched.registry.render_prometheus()
    sched.close()
    assert sched.job(jid).outcome == "completed"
    for family in (
        "pumi_jobs_total", "pumi_queue_depth", "pumi_quanta_total",
        "pumi_aot_hits_total", "pumi_aot_misses_total",
        "pumi_compile_seconds_total", "pumi_job_seconds",
    ):
        assert family in text, family
    # Warm bank over the populated dir: served from hits.
    assert 'pumi_jobs_total{outcome="completed"} 1' in text
    recs = [r["kind"] for r in sched.recorder.records()]
    assert "job_submitted" in recs and "job_done" in recs
    assert "quantum" in recs and "aot" in recs


# --------------------------------------------------------------------- #
# Pipeline shape-key attribution (satellite)
# --------------------------------------------------------------------- #
@pytest.mark.slow
def test_pipeline_batchresult_carries_shape_key(mesh):
    from pumiumtally_tpu.models.pipeline import StreamingTallyPipeline

    pipe = StreamingTallyPipeline(mesh, _cfg(), depth=1)
    cents = np.asarray(mesh.centroids())
    n = 40
    elem = np.arange(n, dtype=np.int32) % mesh.ntet
    origin = cents[elem]
    dest = origin + 0.01
    pipe.submit(origin, dest, elem)
    pipe.submit_source(origin, elem, n_moves=2, source=SourceParams())
    pipe.finish()
    expected = classify(
        mesh.ntet, n, 2, jnp.float32,
        getattr(mesh, "geo20", None) is not None,
    ).key()
    results = list(pipe.results())
    assert len(results) == 2
    assert all(r.shape_key == expected for r in results)
    assert pipe.shape_keys() == {expected: 2}
