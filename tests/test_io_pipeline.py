"""Move-loop I/O pipelining (ops/staging.py, TallyConfig.io_pipeline).

Two structural guarantees, pinned here so the win cannot silently rot:

  * PARITY — io_pipeline="packed" and "overlap" produce BIT-identical
    flux, copied-back positions and material ids to "legacy" on both
    facades, including after a checkpoint restore mid-run (the staging
    records carry float bits through integer carriers, so there is no
    rounding seam to hide behind).
  * TRANSFER COUNT — a steady-state move issues exactly ONE H2D and ONE
    D2H transfer under "packed", executed under
    ``jax.transfer_guard("disallow")`` (which forbids implicit
    transfers on real devices; the guard is inert on the CPU backend,
    so the facade's own byte/transfer accounting — the
    pumi_h2d/d2h_*_total counters — asserts the count everywhere).
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from pumiumtally_tpu import PumiTally, TallyConfig, build_box
from pumiumtally_tpu.mesh.box import build_box_arrays
from pumiumtally_tpu.mesh.core import TetMesh
from pumiumtally_tpu.parallel.partitioned_api import PartitionedTally

N = 128


@pytest.fixture(scope="module")
def mesh64():
    # Two material regions so moves exercise material stops too.
    coords, t2v = build_box_arrays(1.0, 1.0, 1.0, 4, 4, 4)
    cen = coords[t2v].mean(axis=1)
    cls = np.where(cen[:, 0] < 0.5, 1, 2).astype(np.int32)
    return TetMesh.from_numpy(coords, t2v, class_id=cls, dtype=jnp.float64)


def _drive(t, moves=2, seed=17, collect=True):
    rng = np.random.default_rng(seed)
    n = t.num_particles
    pos = rng.uniform(0.05, 0.95, (n, 3))
    t.initialize_particle_location(pos.ravel().copy(), n * 3)
    outs, prev = [], pos
    for _ in range(moves):
        dest = np.clip(prev + rng.normal(0, 0.25, (n, 3)), -0.1, 1.1)
        buf = dest.ravel().copy()
        flying = np.ones(n, np.int8)
        flying[::7] = 0  # parked lanes ride along
        w = rng.uniform(0.5, 2.0, n)
        g = rng.integers(0, 2, n).astype(np.int32)
        mats = np.full(n, 9, np.int32)
        t.move_to_next_location(buf, flying, w, g, mats, buf.size)
        if collect:
            outs.append((buf.reshape(n, 3).copy(), mats.copy()))
        prev = buf.reshape(n, 3).copy()
    return outs


def _move(t, dest, seed=3):
    rng = np.random.default_rng(seed)
    n = t.num_particles
    buf = dest.ravel().copy()
    t.move_to_next_location(
        buf, np.ones(n, np.int8), rng.uniform(0.5, 2.0, n),
        rng.integers(0, 2, n).astype(np.int32), np.full(n, -1, np.int32),
    )
    return buf


# --------------------------------------------------------------------- #
# Parity: packed / overlap bit-identical to legacy
# --------------------------------------------------------------------- #
def _cfg(io):
    return TallyConfig(
        n_groups=2, dtype=jnp.float64, tolerance=1e-8, io_pipeline=io
    )


@pytest.fixture(scope="module")
def single_legacy(mesh64):
    """The legacy-pipeline golden run, driven ONCE for every parity
    comparison below."""
    t = PumiTally(mesh64, N, _cfg("legacy"))
    outs = _drive(t, moves=3)
    return outs, t.raw_flux, t.element_ids, t.total_segments


@pytest.fixture(scope="module")
def part_legacy(mesh64):
    t = PartitionedTally(
        mesh64, N, _cfg("legacy"), n_parts=4, halo_layers=1
    )
    outs = _drive(t)
    return outs, t.raw_flux, t.total_segments


@pytest.mark.parametrize("io", ["packed", "overlap"])
def test_single_chip_pipeline_parity(mesh64, single_legacy, io):
    outs_a, flux_a, elems_a, segs_a = single_legacy
    b = PumiTally(mesh64, N, _cfg(io))
    outs_b = _drive(b, moves=3)
    for (pa, ma), (pb, mb) in zip(outs_a, outs_b):
        np.testing.assert_array_equal(pb, pa)
        np.testing.assert_array_equal(mb, ma)
    np.testing.assert_array_equal(b.raw_flux, flux_a)
    np.testing.assert_array_equal(b.element_ids, elems_a)
    assert b.total_segments == segs_a


@pytest.mark.parametrize("io", ["packed", "overlap"])
def test_partitioned_pipeline_parity(mesh64, part_legacy, io):
    outs_a, flux_a, segs_a = part_legacy
    b = PartitionedTally(
        mesh64, N, _cfg(io), n_parts=4, halo_layers=1
    )
    outs_b = _drive(b)
    for (pa, ma), (pb, mb) in zip(outs_a, outs_b):
        np.testing.assert_array_equal(pb, pa)
        np.testing.assert_array_equal(mb, ma)
    np.testing.assert_array_equal(b.raw_flux, flux_a)
    assert b.total_segments == segs_a


def test_pipeline_parity_with_sorted_layout(mesh64):
    """The device-resident permutation path: with the periodic element
    sort firing every move, packed staging must apply the same slot
    permutation on device that legacy applies on host."""
    kw = dict(
        n_groups=2, dtype=jnp.float64, tolerance=1e-8,
        sort_by_element=True, migration_period=1,
    )
    a = PumiTally(mesh64, N, TallyConfig(io_pipeline="legacy", **kw))
    b = PumiTally(mesh64, N, TallyConfig(io_pipeline="packed", **kw))
    outs_a, outs_b = _drive(a, moves=3), _drive(b, moves=3)
    for (pa, ma), (pb, mb) in zip(outs_a, outs_b):
        np.testing.assert_array_equal(pb, pa)
        np.testing.assert_array_equal(mb, ma)
    np.testing.assert_array_equal(b.raw_flux, a.raw_flux)
    np.testing.assert_array_equal(b.element_ids, a.element_ids)


def test_checkpoint_restore_mid_run_across_pipelines(mesh64, tmp_path):
    """A checkpoint written mid-run under ONE pipeline must resume under
    ANOTHER with bit-identical continuation — the staging layout is
    derived state, never persisted."""
    rng = np.random.default_rng(5)
    dest2 = rng.uniform(0.1, 0.9, (N, 3))

    # Single-chip: legacy writes, packed resumes (and vice versa).
    a = PumiTally(
        mesh64, N,
        TallyConfig(n_groups=2, dtype=jnp.float64, io_pipeline="legacy"),
    )
    _drive(a, moves=2)
    ck = str(tmp_path / "plain.npz")
    a.save_checkpoint(ck)
    b = PumiTally(
        mesh64, N,
        TallyConfig(n_groups=2, dtype=jnp.float64, io_pipeline="packed"),
    )
    b.restore_checkpoint(ck)
    out_a, out_b = _move(a, dest2), _move(b, dest2)
    np.testing.assert_array_equal(out_b, out_a)
    np.testing.assert_array_equal(b.raw_flux, a.raw_flux)

    # Partitioned: packed writes, overlap resumes.
    cfgs = {
        "packed": TallyConfig(
            n_groups=2, dtype=jnp.float64, io_pipeline="packed"
        ),
        "overlap": TallyConfig(
            n_groups=2, dtype=jnp.float64, io_pipeline="overlap"
        ),
    }
    c = PartitionedTally(mesh64, N, cfgs["packed"], n_parts=4)
    _drive(c, moves=2)
    ckp = str(tmp_path / "part.npz")
    c.save_checkpoint(ckp)
    d = PartitionedTally(mesh64, N, cfgs["overlap"], n_parts=4)
    d.restore_checkpoint(ckp)
    out_c, out_d = _move(c, dest2), _move(d, dest2)
    np.testing.assert_array_equal(out_d, out_c)
    np.testing.assert_array_equal(d.raw_flux, c.raw_flux)


# --------------------------------------------------------------------- #
# Transfer-count invariant
# --------------------------------------------------------------------- #
def _io_totals(t):
    totals = t.telemetry()["totals"]
    return {
        k: totals[k]
        for k in ("h2d_transfers", "d2h_transfers", "h2d_bytes",
                  "d2h_bytes")
    }


def test_single_chip_steady_state_one_transfer_each_way():
    mesh = build_box(1.0, 1.0, 1.0, 3, 3, 3)
    t = PumiTally(
        mesh, 64, TallyConfig(tolerance=1e-6, io_pipeline="packed")
    )
    rng = np.random.default_rng(0)
    t.initialize_particle_location(
        rng.uniform(0.1, 0.9, (64, 3)).ravel()
    )
    _move(t, rng.uniform(0.1, 0.9, (64, 3)), seed=1)  # warm/compile
    before = _io_totals(t)
    # "disallow" forbids IMPLICIT transfers: on a real device any stray
    # jnp.asarray staging or np.asarray readback raises here.  (On the
    # CPU backend the guard is inert — the counter delta below carries
    # the assertion everywhere.)
    with jax.transfer_guard("disallow"):
        _move(t, rng.uniform(0.1, 0.9, (64, 3)), seed=2)
    after = _io_totals(t)
    assert after["h2d_transfers"] - before["h2d_transfers"] == 1
    assert after["d2h_transfers"] - before["d2h_transfers"] == 1
    assert after["h2d_bytes"] > before["h2d_bytes"]
    assert after["d2h_bytes"] > before["d2h_bytes"]


def test_partitioned_steady_state_one_transfer_each_way(mesh64):
    # Same N / halo / part count as the parity fixture, so the packed
    # step program is already in the persistent compile cache.
    t = PartitionedTally(
        mesh64, N, _cfg("packed"), n_parts=4, halo_layers=1
    )
    rng = np.random.default_rng(0)
    t.initialize_particle_location(
        rng.uniform(0.1, 0.9, (N, 3)).ravel()
    )
    _move(t, rng.uniform(0.1, 0.9, (N, 3)), seed=1)  # warm/compile
    before = _io_totals(t)
    with jax.transfer_guard("disallow"):
        _move(t, rng.uniform(0.1, 0.9, (N, 3)), seed=2)
    after = _io_totals(t)
    assert after["h2d_transfers"] - before["h2d_transfers"] == 1
    assert after["d2h_transfers"] - before["d2h_transfers"] == 1


def test_legacy_pipeline_counts_more_transfers():
    """The structural claim in reverse: legacy staging really does issue
    several transfers per move (4 H2D / 4 D2H on the single-chip
    facade), so the counters prove the pipeline is doing the work."""
    mesh = build_box(1.0, 1.0, 1.0, 3, 3, 3)
    t = PumiTally(
        mesh, 64, TallyConfig(tolerance=1e-6, io_pipeline="legacy")
    )
    rng = np.random.default_rng(0)
    t.initialize_particle_location(
        rng.uniform(0.1, 0.9, (64, 3)).ravel()
    )
    before = _io_totals(t)
    _move(t, rng.uniform(0.1, 0.9, (64, 3)))
    after = _io_totals(t)
    assert after["h2d_transfers"] - before["h2d_transfers"] == 4
    assert after["d2h_transfers"] - before["d2h_transfers"] >= 3


# --------------------------------------------------------------------- #
# Knob semantics
# --------------------------------------------------------------------- #
def test_io_pipeline_knob_validation_and_overrides(monkeypatch):
    assert TallyConfig().resolve_io_pipeline() == "packed"
    assert TallyConfig(
        io_pipeline="overlap"
    ).resolve_io_pipeline() == "overlap"
    with pytest.raises(ValueError, match="io_pipeline"):
        TallyConfig(io_pipeline="bogus").resolve_io_pipeline()
    # Env override (the CI faults step drives overlap through it).
    monkeypatch.setenv("PUMI_TPU_IO_PIPELINE", "legacy")
    assert TallyConfig(
        io_pipeline="packed"
    ).resolve_io_pipeline() == "legacy"
    monkeypatch.setenv("PUMI_TPU_IO_PIPELINE", "nope")
    with pytest.raises(ValueError, match="io_pipeline"):
        TallyConfig().resolve_io_pipeline()
    monkeypatch.delenv("PUMI_TPU_IO_PIPELINE")
    # Debug surfaces that need the un-packed result force legacy.
    assert TallyConfig(
        record_xpoints=4
    ).resolve_io_pipeline() == "legacy"
    assert TallyConfig(
        checkify_invariants=True
    ).resolve_io_pipeline() == "legacy"


def test_overlap_defers_telemetry_fold():
    """overlap mode: the move's telemetry fold is deferred past the
    move call (truncation warnings stay IN-call — a user-facing
    contract) and flushed at the next read surface — telemetry() must
    drain it."""
    mesh = build_box(1.0, 1.0, 1.0, 3, 3, 3)
    t = PumiTally(
        mesh, 32,
        TallyConfig(
            tolerance=1e-6, io_pipeline="overlap", max_crossings=1
        ),
    )
    rng = np.random.default_rng(0)
    with pytest.warns(RuntimeWarning, match="truncated"):
        t.initialize_particle_location(
            rng.uniform(0.1, 0.9, (32, 3)).ravel()
        )
    # The truncation warning surfaces in-call even though the fold is
    # deferred...
    with pytest.warns(RuntimeWarning, match="truncated"):
        _move(t, rng.uniform(0.1, 0.9, (32, 3)))
    assert t._pending_folds  # fold parked
    # ...and the telemetry read drains the fold (counters land).
    tm = t.telemetry()
    assert not t._pending_folds
    moves = [r for r in tm["per_move"] if r["kind"] == "move"]
    assert len(moves) == 1 and moves[0]["h2d_transfers"] == 1
    assert tm["totals"]["truncated"] > 0
