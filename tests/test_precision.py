"""float32 vs float64 walk agreement (SURVEY.md §7 hard part 3).

The reference's oracle tolerance is 1e-8 in double precision; the TPU fast
path runs float32. This pins how much the f32 walk drifts on the analytic
box scenario: per-element flux within a relative 1e-4 and positions within
~1e-5 of the f64 result — the envelope a user must expect when choosing
TallyConfig(dtype=float32).
"""
from __future__ import annotations

import numpy as np
import pytest

import jax.numpy as jnp

from pumiumtally_tpu import make_flux
from pumiumtally_tpu.mesh.box import build_box_arrays
from pumiumtally_tpu.mesh.core import TetMesh
from pumiumtally_tpu.ops.walk import trace_impl


def _run(dtype, tol, **kw):
    coords, tets = build_box_arrays(1.0, 1.0, 1.0, 5, 5, 5)
    cid = (coords[tets].mean(axis=1)[:, 0] > 0.5).astype(np.int32)
    mesh = TetMesh.from_numpy(coords, tets, cid, dtype=dtype)
    rng = np.random.default_rng(3)
    n = 256
    elem = rng.integers(0, mesh.ntet, n).astype(np.int32)
    origin = np.asarray(mesh.centroids())[elem]
    dest = rng.uniform(-0.05, 1.05, (n, 3))
    weight = rng.uniform(0.5, 2.0, n)
    r = trace_impl(
        mesh,
        jnp.asarray(origin, dtype),
        jnp.asarray(dest, dtype),
        jnp.asarray(elem),
        jnp.ones(n, bool),
        jnp.asarray(weight, dtype),
        jnp.asarray(rng.integers(0, 2, n), jnp.int32),
        jnp.full(n, -1, jnp.int32),
        make_flux(mesh.ntet, 2, dtype),
        initial=False,
        max_crossings=mesh.ntet + 8,
        tolerance=tol,
        **kw,
    )
    return r


def test_f32_tracks_f64_envelope():
    r64 = _run(jnp.float64, 1e-8)
    r32 = _run(jnp.float32, 1e-6)
    f64 = np.asarray(r64.flux)[..., 0]
    f32 = np.asarray(r32.flux)[..., 0]
    # Total track length agrees tightly; per-element within the f32
    # envelope (crossing points move by ~eps relative to tet size).
    assert abs(f32.sum() - f64.sum()) <= 1e-4 * f64.sum()
    np.testing.assert_allclose(f32, f64, atol=5e-4 * f64.max())
    np.testing.assert_allclose(
        np.asarray(r32.position), np.asarray(r64.position), atol=1e-4
    )
    # Boundary/material decisions must agree except for rays that graze a
    # face within the f32 tolerance band (none in this seeded scenario).
    np.testing.assert_array_equal(
        np.asarray(r32.material_id), np.asarray(r64.material_id)
    )
    assert bool(np.asarray(r32.done).all())


def test_f64_run_to_run_reproducible():
    """Same-config f64 runs are bit-identical — the reproducibility the
    1e-8 oracle relies on."""
    r_a = _run(jnp.float64, 1e-8)
    r_b = _run(jnp.float64, 1e-8)
    np.testing.assert_array_equal(
        np.asarray(r_a.flux), np.asarray(r_b.flux)
    )


@pytest.mark.slow
def test_f64_stable_across_scheduling():
    """Changing lane scheduling (staged compaction + unroll) reorders the
    scatter-adds; in f64 the result must stay within accumulation noise of
    the flat loop (well inside the 1e-8 oracle tolerance)."""
    r_a = _run(jnp.float64, 1e-8)
    r_b = _run(
        jnp.float64, 1e-8,
        compact_stages=((4, 128), (8, 64), (16, 32)), unroll=4,
    )
    np.testing.assert_allclose(
        np.asarray(r_a.flux), np.asarray(r_b.flux), rtol=0, atol=1e-12
    )
    np.testing.assert_array_equal(
        np.asarray(r_a.material_id), np.asarray(r_b.material_id)
    )


def test_f32_grazing_ray_tolerance_semantics():
    """Round-1 task 3's acceptance test (VERDICT round-2 item 3c): an f32
    destination within the geometric tolerance band of an interior face
    must count as INSIDE the current element (reached, no hop), while a
    destination past the band crosses — here onto a material boundary, so
    it stops clipped on the plane with the far side's class id. Both
    semantics asserted in float32 with the geometric tolerance 1e-6.
    """
    coords, tets = build_box_arrays(1.0, 1.0, 1.0, 2, 1, 1)
    # Left cell (x<0.5) class 3, right cell class 9: the x=0.5 plane is
    # both an interior face and a material boundary.
    cid = np.where(
        coords[tets].mean(axis=1)[:, 0] < 0.5, 3, 9
    ).astype(np.int32)
    mesh = TetMesh.from_numpy(coords, tets, cid, dtype=jnp.float32)
    cents = np.asarray(mesh.centroids())
    e0 = int(np.argmin(np.abs(cents[:, 0] - 0.25)))  # a left-cell element
    origin = cents[e0:e0 + 1]
    tol = 1e-6

    def run(d_beyond):
        dest = origin.copy()
        dest[0, 0] = 0.5 + d_beyond  # graze the x=0.5 plane by d_beyond
        r = trace_impl(
            mesh,
            jnp.asarray(origin, jnp.float32),
            jnp.asarray(dest, jnp.float32),
            jnp.asarray([e0], jnp.int32),
            jnp.ones(1, bool),
            jnp.ones(1, jnp.float32),
            jnp.zeros(1, jnp.int32),
            jnp.full(1, -1, jnp.int32),
            make_flux(mesh.ntet, 1, jnp.float32),
            initial=False,
            max_crossings=mesh.ntet + 8,
            tolerance=tol,
        )
        assert bool(np.asarray(r.done).all())
        return (
            int(np.asarray(r.elem)[0]),
            int(np.asarray(r.material_id)[0]),
            np.asarray(r.position)[0],
        )

    # Inside the band (1e-8..1e-6 of the face): reached-at-destination
    # semantics — no hop, no material stop, position = destination.
    for d in (1e-8, 1e-7, 5e-7):
        elem, mat, pos = run(d)
        assert int(np.asarray(mesh.class_id)[elem]) == 3, (
            f"d={d}: grazing destination must stay in the near element"
        )
        assert mat == -1  # plain reached, not a material stop
        # The reached position is the tolerance-band intersection point,
        # within the geometric tolerance of the true destination.
        assert abs(pos[0] - np.float32(0.5 + d)) <= tol + 2e-7

    # Past the band: a genuine crossing -> material stop ON the plane
    # with the far side's class id, parent element hopped across
    # (reference cpp:452-515 semantics).
    elem, mat, pos = run(1e-3)
    assert int(np.asarray(mesh.class_id)[elem]) == 9
    assert mat == 9
    assert abs(pos[0] - 0.5) < 1e-6  # clipped to the intersection point
