"""Round-trip of the .osh-subset mesh directory format (VERDICT round-2
item 7): build_box → write_osh → load_mesh must reproduce identical
connectivity, coordinates, and class ids; genuine Omega_h streams are
rejected with a pointer at the offline converter instead of misparsed."""
from __future__ import annotations

import os

import numpy as np
import pytest

import jax.numpy as jnp

from pumiumtally_tpu.mesh.box import build_box_arrays
from pumiumtally_tpu.mesh.io import load_mesh
from pumiumtally_tpu.mesh.osh import read_osh, write_osh


def test_osh_roundtrip(tmp_path):
    coords, tets = build_box_arrays(1.0, 2.0, 3.0, 3, 2, 4)
    cid = (np.arange(tets.shape[0]) % 5).astype(np.int32)
    path = str(tmp_path / "mesh.osh")
    write_osh(path, coords, tets, cid)
    assert os.path.isfile(os.path.join(path, "nparts"))
    assert os.path.isfile(os.path.join(path, "0.osh"))

    rc, rt, rcid = read_osh(path)
    np.testing.assert_array_equal(rc, coords)
    np.testing.assert_array_equal(rt, tets)
    np.testing.assert_array_equal(rcid, cid)

    # Through the generic loader: a walkable TetMesh with the same
    # connectivity-derived tables as the in-memory build.
    mesh = load_mesh(path, dtype=jnp.float64)
    assert mesh.ntet == tets.shape[0]
    direct = __import__(
        "pumiumtally_tpu.mesh.core", fromlist=["TetMesh"]
    ).TetMesh.from_numpy(coords, tets, cid, dtype=jnp.float64)
    np.testing.assert_array_equal(
        np.asarray(mesh.tet2tet), np.asarray(direct.tet2tet)
    )
    np.testing.assert_array_equal(
        np.asarray(mesh.class_id), np.asarray(direct.class_id)
    )
    np.testing.assert_allclose(
        np.asarray(mesh.volumes), np.asarray(direct.volumes), rtol=1e-12
    )


def test_osh_foreign_stream_rejected(tmp_path):
    path = tmp_path / "foreign.osh"
    path.mkdir()
    (path / "nparts").write_text("1\n")
    # A stream that is not ours (e.g. genuine Omega_h bytes).
    (path / "0.osh").write_bytes(b"\x00mega_h!" + b"\x00" * 64)
    with pytest.raises(NotImplementedError, match="osh2npz"):
        read_osh(str(path))


def test_osh_missing_nparts(tmp_path):
    d = tmp_path / "empty.osh"
    d.mkdir()
    with pytest.raises(FileNotFoundError, match="nparts"):
        read_osh(str(d))


def test_osh2npz_emitter_roundtrip(tmp_path):
    """Compile native/osh2npz.cpp against the minimal Omega_h API stub in
    tests/osh2npz_stub (the real library is absent here) and check numpy
    loads the .npz it emits bit-exactly — validating the tool's zip/npy
    emitter end to end, which is everything except Omega_h's own reader."""
    import shutil
    import subprocess

    gxx = shutil.which("g++")
    if gxx is None:
        pytest.skip("no g++ in environment")
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    exe = str(tmp_path / "osh2npz")
    r = subprocess.run(
        [
            gxx, "-std=c++17", "-O1",
            "-I", os.path.join(root, "tests", "osh2npz_stub"),
            os.path.join(root, "native", "osh2npz.cpp"),
            "-o", exe,
        ],
        capture_output=True, text=True,
    )
    assert r.returncode == 0, r.stderr[-2000:]
    out = str(tmp_path / "out.npz")
    r = subprocess.run([exe, "fake.osh", out], capture_output=True, text=True)
    assert r.returncode == 0, r.stderr[-2000:]
    z = np.load(out)
    assert sorted(z.files) == ["class_id", "coords", "tet2vert"]
    assert z["coords"].shape == (5, 3) and z["coords"].dtype == np.float64
    np.testing.assert_array_equal(
        z["tet2vert"], [[0, 1, 2, 3], [1, 2, 3, 4]]
    )
    np.testing.assert_array_equal(z["class_id"], [7, 9])
    # The stub's coords row 1 is the unit-x vertex.
    np.testing.assert_array_equal(z["coords"][1], [1.0, 0.0, 0.0])


def test_osh_multipart_concatenates(tmp_path):
    """A multi-part directory (one stream per rank) concatenates parts
    with per-part vertex offsets."""
    import struct

    from pumiumtally_tpu.mesh.osh import MAGIC

    coords, tets = build_box_arrays(1.0, 1.0, 1.0, 2, 2, 2)
    cid = np.arange(tets.shape[0], dtype=np.int32) % 3
    path = str(tmp_path / "two.osh")
    # Write part 0 via write_osh, then append a second part by hand and
    # bump nparts.
    write_osh(path, coords, tets, cid)
    coords2 = coords + 10.0
    with open(os.path.join(path, "1.osh"), "wb") as f:
        f.write(MAGIC)
        f.write(struct.pack("<i", 3))
        f.write(struct.pack("<q", coords2.shape[0]))
        f.write(struct.pack("<q", tets.shape[0]))
        f.write(coords2.astype("<f8").tobytes())
        f.write(tets.astype("<i4").tobytes())
        f.write((cid + 100).astype("<i4").tobytes())
    with open(os.path.join(path, "nparts"), "w") as f:
        f.write("2\n")

    rc, rt, rcid = read_osh(path)
    nv, nt = coords.shape[0], tets.shape[0]
    assert rc.shape == (2 * nv, 3) and rt.shape == (2 * nt, 4)
    np.testing.assert_array_equal(rc[:nv], coords)
    np.testing.assert_array_equal(rc[nv:], coords2)
    np.testing.assert_array_equal(rt[:nt], tets)
    np.testing.assert_array_equal(rt[nt:], tets + nv)  # offset applied
    np.testing.assert_array_equal(rcid[nt:], cid + 100)
