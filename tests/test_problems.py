"""Benchmark-problem geometry constructors."""
from __future__ import annotations

import numpy as np

from pumiumtally_tpu.models.problems import assembly, pincell, unit_cube


def test_unit_cube_counts():
    m = unit_cube(4)
    assert m.ntet == 6 * 4**3
    assert np.all(np.asarray(m.class_id) == 0)


def test_pincell_regions():
    m = pincell(8, pin_radius=0.3)
    cid = np.asarray(m.class_id)
    assert set(np.unique(cid)) == {0, 1}
    # Pin occupies roughly pi*r^2 of the cross-section.
    frac = (cid == 1).mean()
    assert 0.5 * np.pi * 0.09 < frac < 1.6 * np.pi * 0.09


def test_assembly_lattice_ids():
    m = assembly(cells=12, lattice=3)
    cid = np.asarray(m.class_id)
    pins = set(np.unique(cid)) - {0}
    assert pins == set(range(1, 10))
    # Each pin region is spatially coherent: its centroids cluster inside
    # one lattice cell.
    coords = np.asarray(m.coords)
    tets = np.asarray(m.tet2vert)
    centroids = coords[tets].mean(axis=1)
    for pid in pins:
        i, j = (pid - 1) // 3, (pid - 1) % 3
        c = centroids[cid == pid][:, :2]
        assert np.all(c[:, 0] >= i / 3 - 1e-9)
        assert np.all(c[:, 0] <= (i + 1) / 3 + 1e-9)
        assert np.all(c[:, 1] >= j / 3 - 1e-9)
        assert np.all(c[:, 1] <= (j + 1) / 3 + 1e-9)
