"""Distributed-mesh partitioning + migration tests (8 virtual CPU devices).

Closes the reference's biggest untested area: multi-rank distributed mesh
with cross-rank particle migration is advertised (README.md:10) and plumbed
(`search(migrate)`, pumipic_particle_data_structure.cpp:256-258, 763) but
never exercised in its test suite (SURVEY.md §4). The oracle here is the
single-chip fused walk itself, which in turn is pinned to the reference's
analytic box oracle by test_tally_oracle.py — the partitioned walk must
reproduce its flux, final positions, parent elements, and material ids
exactly (same arithmetic, same dtype, so equality is to ~1e-12 in f64).
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from pumiumtally_tpu import build_box, make_flux
from pumiumtally_tpu.mesh.core import TetMesh
from pumiumtally_tpu.ops.walk import trace_impl
from pumiumtally_tpu.ops.walk_partitioned import (
    collect_by_particle_id,
    distribute_particles,
    make_partitioned_step,
)
from pumiumtally_tpu.parallel.mesh_partition import (
    assemble_global_flux,
    decode_remote,
    morton_order,
    partition_mesh,
)
from pumiumtally_tpu.parallel.particle_sharding import make_device_mesh

DTYPE = jnp.float64
N_DEV = 8


@pytest.fixture(scope="module")
def box():
    return build_box(1.0, 1.0, 1.0, 4, 4, 4, dtype=DTYPE)  # 384 tets


@pytest.fixture(scope="module")
def two_region_box():
    """Box with class_id split at x=0.5 → material boundary in the middle."""
    from pumiumtally_tpu.mesh.box import build_box_arrays

    coords, tet2vert = build_box_arrays(1.0, 1.0, 1.0, 4, 4, 4)
    centroids = coords[tet2vert].mean(axis=1)
    class_id = np.where(centroids[:, 0] < 0.5, 1, 2).astype(np.int32)
    return TetMesh.from_numpy(coords, tet2vert, class_id, dtype=DTYPE)


def test_partition_covers_and_balances(box):
    part = partition_mesh(box, N_DEV)
    assert part.owner.shape == (box.ntet,)
    assert part.counts.sum() == box.ntet
    assert part.counts.max() - part.counts.min() <= 1
    # local2global/global2local are mutually inverse on owned entries.
    for p in range(N_DEV):
        l2g = part.local2global[p, : part.counts[p]]
        assert np.all(part.owner[l2g] == p)
        assert np.all(part.global2local[l2g] == np.arange(part.counts[p]))


def test_partition_adjacency_encoding(box):
    part = partition_mesh(box, N_DEV)
    t2t = np.asarray(box.tet2tet)
    enc = np.asarray(part.tet2tet_enc)
    ncls = np.asarray(part.nbr_class)
    cls = np.asarray(box.class_id)
    for p in range(N_DEV):
        for li in range(int(part.counts[p])):
            g = part.local2global[p, li]
            for f in range(4):
                nb = t2t[g, f]
                e = enc[p, li, f]
                if nb < 0:
                    assert e == -1
                    assert ncls[p, li, f] == cls[g]
                elif part.owner[nb] == p:
                    assert e == part.global2local[nb]
                    assert ncls[p, li, f] == cls[nb]
                else:
                    owner, loc = decode_remote(e, part.max_local)
                    assert owner == part.owner[nb]
                    assert loc == part.global2local[nb]
                    assert part.local2global[owner, loc] == nb
                    assert ncls[p, li, f] == cls[nb]
    # Padded rows are inert.
    for p in range(N_DEV):
        assert np.all(enc[p, int(part.counts[p]) :] == -1)


def _random_batch(mesh, n, seed, spread=0.9):
    rng = np.random.default_rng(seed)
    elem = rng.integers(0, mesh.ntet, n).astype(np.int32)
    origin = np.asarray(mesh.centroids())[elem]
    dest = origin + rng.uniform(-spread, spread, (n, 3))
    dest = np.clip(dest, -0.2, 1.2)  # some leave the domain
    weight = rng.uniform(0.5, 2.0, n)
    group = rng.integers(0, 2, n).astype(np.int32)
    return elem, origin, dest, weight, group


def _single_chip(mesh, elem, origin, dest, weight, group, n_groups=2):
    return trace_impl(
        mesh,
        jnp.asarray(origin, DTYPE),
        jnp.asarray(dest, DTYPE),
        jnp.asarray(elem),
        jnp.ones(len(elem), bool),
        jnp.asarray(weight, DTYPE),
        jnp.asarray(group),
        jnp.full(len(elem), -1, jnp.int32),
        make_flux(mesh.ntet, n_groups, DTYPE),
        initial=False,
        max_crossings=mesh.ntet + 8,
        tolerance=1e-8,
    )


def _partitioned(mesh, part, elem, origin, dest, weight, group,
                 n_groups=2, exchange_size=None, max_rounds=None,
                 unroll=1, compact_after=None, compact_size=None,
                 compact_stages=None, tally_scatter="pair",
                 flat_flux=False):
    n = len(elem)
    dmesh = make_device_mesh(N_DEV)
    placed = distribute_particles(
        part,
        dmesh,
        elem,
        dict(
            origin=np.asarray(origin, np.float64),
            dest=np.asarray(dest, np.float64),
            weight=np.asarray(weight, np.float64),
            group=np.asarray(group, np.int32),
            material_id=np.full(n, -1, np.int32),
        ),
    )
    step = make_partitioned_step(
        dmesh,
        part,
        n_groups=n_groups,
        max_crossings=mesh.ntet + 8,
        tolerance=1e-8,
        exchange_size=exchange_size,
        max_rounds=max_rounds,
        unroll=unroll,
        compact_after=compact_after,
        compact_size=compact_size,
        compact_stages=compact_stages,
        tally_scatter=tally_scatter,
    )
    from jax.sharding import NamedSharding, PartitionSpec as P

    flux_shape = (
        (N_DEV, part.max_local * n_groups * 2)
        if flat_flux
        else (N_DEV, part.max_local, n_groups, 2)
    )
    flux = jax.device_put(
        jnp.zeros(flux_shape, DTYPE), NamedSharding(dmesh, P("p"))
    )
    done0 = jnp.zeros_like(placed["valid"])
    res = step(
        placed["origin"].astype(DTYPE),
        placed["dest"].astype(DTYPE),
        placed["elem"],
        done0,
        placed["material_id"],
        placed["weight"].astype(DTYPE),
        placed["group"],
        placed["particle_id"],
        placed["valid"],
        flux,
    )
    return res, collect_by_particle_id(res, n)


def test_partitioned_matches_single_chip(box):
    part = partition_mesh(box, N_DEV)
    elem, origin, dest, weight, group = _random_batch(box, 96, seed=3)
    ref = _single_chip(box, elem, origin, dest, weight, group)
    res, got = _partitioned(box, part, elem, origin, dest, weight, group)

    assert int(np.sum(np.asarray(res.n_dropped))) == 0
    assert got["done"].all()
    g_flux = assemble_global_flux(part, res.flux)
    np.testing.assert_allclose(
        g_flux, np.asarray(ref.flux), rtol=0, atol=1e-12
    )
    np.testing.assert_allclose(
        got["position"], np.asarray(ref.position), atol=1e-12
    )
    np.testing.assert_array_equal(got["material_id"], np.asarray(ref.material_id))
    # Recover global parent element of each particle from its final chip.
    pid = np.asarray(res.particle_id)
    valid = np.asarray(res.valid)
    elem_l = np.asarray(res.elem)
    cap = pid.shape[0] // N_DEV
    chip = np.arange(pid.shape[0]) // cap
    got_global = np.zeros(len(elem), np.int64)
    sel = valid & (pid >= 0)
    got_global[pid[sel]] = part.local2global[chip[sel], elem_l[sel]]
    np.testing.assert_array_equal(got_global, np.asarray(ref.elem))
    assert int(np.sum(np.asarray(res.n_segments))) == int(ref.n_segments)
    # Conservation ledger across cuts: each particle's scored track
    # length (which migrates with it) must equal the single-chip
    # walk's — a double- or missed-scored cut segment shows up here.
    np.testing.assert_allclose(
        got["track_length"], np.asarray(ref.track_length), atol=1e-12
    )


def test_partitioned_material_boundaries(two_region_box):
    mesh = two_region_box
    part = partition_mesh(mesh, N_DEV)
    # Rays crossing x=0.5 must stop at the material interface.
    n = 40
    elem, origin, dest, weight, group = _random_batch(mesh, n, seed=7)
    # Force crossings: send everything toward the far half in x.
    dest[:, 0] = np.where(origin[:, 0] < 0.5, 0.95, 0.05)
    ref = _single_chip(mesh, elem, origin, dest, weight, group)
    res, got = _partitioned(mesh, part, elem, origin, dest, weight, group)
    assert got["done"].all()
    np.testing.assert_array_equal(got["material_id"], np.asarray(ref.material_id))
    np.testing.assert_allclose(
        got["position"], np.asarray(ref.position), atol=1e-12
    )
    g_flux = assemble_global_flux(part, res.flux)
    np.testing.assert_allclose(
        g_flux, np.asarray(ref.flux), rtol=0, atol=1e-12
    )
    # Material stops actually happened (some particles report the far region).
    assert (got["material_id"] >= 1).any()


def test_partitioned_small_exchange_buffer(box):
    """Exchange-buffer overflow only delays migration (extra rounds), never
    loses particles."""
    part = partition_mesh(box, N_DEV)
    elem, origin, dest, weight, group = _random_batch(box, 64, seed=11)
    ref = _single_chip(box, elem, origin, dest, weight, group)
    res, got = _partitioned(
        box, part, elem, origin, dest, weight, group,
        exchange_size=2, max_rounds=256,
    )
    assert int(np.sum(np.asarray(res.n_dropped))) == 0
    assert got["done"].all()
    g_flux = assemble_global_flux(part, res.flux)
    np.testing.assert_allclose(g_flux, np.asarray(ref.flux), atol=1e-12)
    assert int(np.asarray(res.n_rounds)[0]) > 1


@pytest.mark.slow
def test_partitioned_unroll_matches(box):
    """The dispatch-amortizing unroll must not change partitioned results
    (done lanes and migration-frozen lanes are no-ops in the body)."""
    part = partition_mesh(box, N_DEV)
    elem, origin, dest, weight, group = _random_batch(box, 48, seed=13)
    _, base = _partitioned(box, part, elem, origin, dest, weight, group)
    res, got = _partitioned(
        box, part, elem, origin, dest, weight, group, unroll=4
    )
    assert got["done"].all()
    np.testing.assert_allclose(
        got["position"], base["position"], atol=1e-12
    )
    np.testing.assert_array_equal(got["material_id"], base["material_id"])


@pytest.mark.slow
def test_partitioned_compaction_matches(box):
    """Straggler compaction in the partitioned walk phase must not change
    results — it only reschedules lanes (migration-frozen lanes drop out
    of the compacted subsets like done lanes do)."""
    part = partition_mesh(box, N_DEV)
    elem, origin, dest, weight, group = _random_batch(box, 64, seed=17)
    ref = _single_chip(box, elem, origin, dest, weight, group)
    res, got = _partitioned(
        box, part, elem, origin, dest, weight, group,
        compact_after=2, compact_size=8, unroll=2,
    )
    assert int(np.sum(np.asarray(res.n_dropped))) == 0
    assert got["done"].all()
    g_flux = assemble_global_flux(part, res.flux)
    np.testing.assert_allclose(g_flux, np.asarray(ref.flux), atol=1e-12)
    np.testing.assert_allclose(
        got["position"], np.asarray(ref.position), atol=1e-12
    )
    np.testing.assert_array_equal(
        got["material_id"], np.asarray(ref.material_id)
    )
    assert int(np.sum(np.asarray(res.n_segments))) == int(ref.n_segments)


def test_partitioned_interleaved_scatter_matches(box):
    """The interleaved tally-scatter strategy in the partitioned body
    must be bit-identical to the default pair (disjoint flat slots) —
    keeps the non-default branch of the hardware A/B covered."""
    part = partition_mesh(box, N_DEV)
    elem, origin, dest, weight, group = _random_batch(box, 64, seed=29)
    ref = _single_chip(box, elem, origin, dest, weight, group)
    res, got = _partitioned(
        box, part, elem, origin, dest, weight, group,
        tally_scatter="interleaved",
    )
    assert int(np.sum(np.asarray(res.n_dropped))) == 0
    g_flux = assemble_global_flux(part, res.flux)
    np.testing.assert_allclose(g_flux, np.asarray(ref.flux), atol=1e-12)
    assert int(np.sum(np.asarray(res.n_segments))) == int(ref.n_segments)


@pytest.mark.slow
def test_partitioned_staged_ladder_matches(box):
    """The staged compaction ladder (with per-stage unroll overrides)
    in the partitioned walk phase must not change results — same
    contract as the single-stage knobs, denser scheduling."""
    part = partition_mesh(box, N_DEV)
    elem, origin, dest, weight, group = _random_batch(box, 64, seed=23)
    ref = _single_chip(box, elem, origin, dest, weight, group)
    res, got = _partitioned(
        box, part, elem, origin, dest, weight, group,
        compact_stages=((2, 24), (4, 16, 4), (8, 8, 8)), unroll=2,
    )
    assert int(np.sum(np.asarray(res.n_dropped))) == 0
    assert got["done"].all()
    g_flux = assemble_global_flux(part, res.flux)
    np.testing.assert_allclose(g_flux, np.asarray(ref.flux), atol=1e-12)
    np.testing.assert_allclose(
        got["position"], np.asarray(ref.position), atol=1e-12
    )
    np.testing.assert_allclose(
        got["track_length"], np.asarray(ref.track_length), atol=1e-12
    )
    assert int(np.sum(np.asarray(res.n_segments))) == int(ref.n_segments)


@pytest.mark.parametrize("halo", [0, 1])
def test_partitioned_flat_flux_matches(box, halo):
    """The flat per-chip slab layout ([n_parts, max_local*g*2] — the TPU
    production layout, see core.tally.make_flux on the 64× tile padding)
    must be a pure re-indexing of the 3-D slabs: every output equal, the
    flux equal after reshape. Covers the halo fold's transient 3-D view."""
    part = partition_mesh(box, N_DEV, halo_layers=halo)
    elem, origin, dest, weight, group = _random_batch(box, 96, seed=3)
    res3, got3 = _partitioned(box, part, elem, origin, dest, weight, group)
    resf, gotf = _partitioned(
        box, part, elem, origin, dest, weight, group, flat_flux=True
    )
    assert resf.flux.shape == (N_DEV, part.max_local * 2 * 2)
    np.testing.assert_array_equal(
        np.asarray(resf.flux).reshape(N_DEV, part.max_local, 2, 2),
        np.asarray(res3.flux),
    )
    np.testing.assert_array_equal(gotf["position"], got3["position"])
    np.testing.assert_array_equal(gotf["material_id"], got3["material_id"])
    np.testing.assert_array_equal(
        gotf["track_length"], got3["track_length"]
    )
    assert int(np.sum(np.asarray(resf.n_segments))) == int(
        np.sum(np.asarray(res3.n_segments))
    )


def test_partitioned_64_groups_matches_single_chip(box):
    """Config-4 × config-3 corner: 64 energy groups over the partitioned
    walk (flat slabs). The per-shard flat keys (elem_local*64+group)*2
    must land exactly where the single-chip walk's global keys do."""
    g = 64
    part = partition_mesh(box, N_DEV, halo_layers=1)
    rng = np.random.default_rng(21)
    n = 96
    elem = rng.integers(0, box.ntet, n).astype(np.int32)
    origin = np.asarray(box.centroids())[elem]
    dest = rng.uniform(-0.1, 1.1, (n, 3))
    weight = rng.uniform(0.5, 2.0, n)
    group = rng.integers(0, g, n).astype(np.int32)
    ref = trace_impl(
        box,
        jnp.asarray(origin, DTYPE),
        jnp.asarray(dest, DTYPE),
        jnp.asarray(elem),
        jnp.ones(n, bool),
        jnp.asarray(weight, DTYPE),
        jnp.asarray(group),
        jnp.full(n, -1, jnp.int32),
        make_flux(box.ntet, g, DTYPE, flat=True),
        n_groups=g,
        initial=False,
        max_crossings=box.ntet + 8,
        tolerance=1e-8,
    )
    res, got = _partitioned(
        box, part, elem, origin, dest, weight, group, n_groups=g,
        flat_flux=True,
    )
    assert int(np.sum(np.asarray(res.n_dropped))) == 0
    g_flux = assemble_global_flux(
        part,
        np.asarray(res.flux).reshape(N_DEV, part.max_local, g, 2),
    )
    np.testing.assert_allclose(
        g_flux,
        np.asarray(ref.flux).reshape(box.ntet, g, 2),
        rtol=0,
        atol=1e-12,
    )
    np.testing.assert_allclose(
        got["position"], np.asarray(ref.position), atol=1e-12
    )


def test_morton_order_is_permutation():
    rng = np.random.default_rng(0)
    pts = rng.uniform(size=(500, 3))
    order = morton_order(pts)
    assert sorted(order.tolist()) == list(range(500))


# --------------------------------------------------------------------------- #
# Halo (buffered picparts — the reference's Pumi-PIC buffering model,
# cpp:865-876, with depth as a knob instead of full-mesh replication).
# --------------------------------------------------------------------------- #
def test_partition_halo_tables(box):
    part0 = partition_mesh(box, N_DEV)
    part = partition_mesh(box, N_DEV, halo_layers=1)
    t2t = np.asarray(box.tet2tet)
    assert part.halo_layers == 1 and part.row_owner is not None
    assert np.array_equal(part.counts, part0.counts)  # owned unchanged
    row_owner = np.asarray(part.row_owner)
    row_owner_local = np.asarray(part.row_owner_local)
    for p in range(N_DEV):
        n_owned = int(part.counts[p])
        rows = part.local2global[p]
        n_rows = int((rows >= 0).sum())
        assert n_rows > n_owned  # a 8-way box split always has a halo
        # Owned block first, then halo rows owned elsewhere.
        assert np.all(part.owner[rows[:n_owned]] == p)
        assert np.all(part.owner[rows[n_owned:n_rows]] != p)
        # row_owner/_local give each row's canonical home.
        assert np.all(row_owner[p, :n_rows] == part.owner[rows[:n_rows]])
        assert np.all(
            row_owner_local[p, :n_rows]
            == part.global2local[rows[:n_rows]]
        )
        # 1-layer halo = exactly the face neighbors of owned elements
        # that are owned elsewhere.
        expect = set()
        for g in rows[:n_owned]:
            for nb in t2t[g]:
                if nb >= 0 and part.owner[nb] != p:
                    expect.add(int(nb))
        assert set(rows[n_owned:n_rows].tolist()) == expect
    # Send/recv fold tables pair each sender halo row with its owner row.
    hs = np.asarray(part.halo_send_rows)
    hr = np.asarray(part.halo_recv_rows)
    for p in range(N_DEV):
        for q in range(N_DEV):
            sl = hs[p, q][hs[p, q] < part.max_local]
            rl = hr[q, p][hr[q, p] < part.max_local]
            assert len(sl) == len(rl)
            for s, r in zip(sl, rl):
                g = part.local2global[p, s]
                assert part.owner[g] == q
                assert part.local2global[q, r] == g


@pytest.mark.parametrize("halo", [1, 2])
def test_partitioned_halo_matches_single_chip(box, halo):
    """Guests walk and score through buffered elements; results must stay
    EXACTLY the single-chip walk's (the guest-flux fold is an exact
    permutation-sum) while migration rounds drop."""
    part0 = partition_mesh(box, N_DEV)
    part = partition_mesh(box, N_DEV, halo_layers=halo)
    elem, origin, dest, weight, group = _random_batch(box, 96, seed=3)
    ref = _single_chip(box, elem, origin, dest, weight, group)
    res0, _ = _partitioned(box, part0, elem, origin, dest, weight, group)
    res, got = _partitioned(box, part, elem, origin, dest, weight, group)

    assert int(np.sum(np.asarray(res.n_dropped))) == 0
    assert got["done"].all()
    g_flux = assemble_global_flux(part, res.flux)
    np.testing.assert_allclose(
        g_flux, np.asarray(ref.flux), rtol=0, atol=1e-12
    )
    np.testing.assert_allclose(
        got["position"], np.asarray(ref.position), atol=1e-12
    )
    np.testing.assert_array_equal(
        got["material_id"], np.asarray(ref.material_id)
    )
    np.testing.assert_allclose(
        got["track_length"], np.asarray(ref.track_length), atol=1e-12
    )
    assert int(np.sum(np.asarray(res.n_segments))) == int(ref.n_segments)
    # elem_global resolves guests through the holding chip's map.
    got2 = collect_by_particle_id(res, len(elem), part)
    np.testing.assert_array_equal(got2["elem_global"], np.asarray(ref.elem))
    # Never MORE rounds than unbuffered (this 384-tet box finishes in 2
    # rounds either way; the actual reduction is asserted at a size where
    # cut ping-pong exists, test_halo_cuts_migration_rounds).
    r0 = int(np.asarray(res0.n_rounds)[0])
    r1 = int(np.asarray(res.n_rounds)[0])
    assert r1 <= r0, (r1, r0)
    # Halo rows come back zeroed so accumulating flux across steps cannot
    # double-fold guest contributions.
    slabs = np.asarray(res.flux)
    for p in range(N_DEV):
        assert np.all(slabs[p, int(part.counts[p]):] == 0.0)


def test_partitioned_halo_material_boundaries(two_region_box):
    mesh = two_region_box
    part = partition_mesh(mesh, N_DEV, halo_layers=1)
    n = 40
    elem, origin, dest, weight, group = _random_batch(mesh, n, seed=7)
    dest[:, 0] = np.where(origin[:, 0] < 0.5, 0.95, 0.05)
    ref = _single_chip(mesh, elem, origin, dest, weight, group)
    res, got = _partitioned(mesh, part, elem, origin, dest, weight, group)
    assert got["done"].all()
    np.testing.assert_array_equal(
        got["material_id"], np.asarray(ref.material_id)
    )
    np.testing.assert_allclose(
        got["position"], np.asarray(ref.position), atol=1e-12
    )
    g_flux = assemble_global_flux(part, res.flux)
    np.testing.assert_allclose(
        g_flux, np.asarray(ref.flux), rtol=0, atol=1e-12
    )
    assert (got["material_id"] >= 1).any()


@pytest.mark.slow
def test_halo_cuts_migration_rounds():
    """At a size where Morton-cut ping-pong exists (round_stats showed a
    geometric pending tail at 1M tets; short rays near jagged tet-level
    cuts reproduce it at 10k), the halo must cut the walk/exchange round
    count at identical results (measured: 3 → 2 → 1 rounds at depths
    0 / 1 / 4 on this config)."""
    mesh = build_box(1.0, 1.0, 1.0, 12, 12, 12, dtype=DTYPE)  # 10368 tets
    n = 512
    rng = np.random.default_rng(0)
    elem = rng.integers(0, mesh.ntet, n).astype(np.int32)
    origin = np.asarray(mesh.centroids())[elem]
    dest = np.clip(origin + rng.normal(0, 0.12, (n, 3)), 0.01, 0.99)
    weight = np.ones(n)
    group = np.zeros(n, np.int32)
    ref = _single_chip(mesh, elem, origin, dest, weight, group, n_groups=1)
    part0 = partition_mesh(mesh, N_DEV)
    part1 = partition_mesh(mesh, N_DEV, halo_layers=1)
    res0, _ = _partitioned(
        mesh, part0, elem, origin, dest, weight, group, n_groups=1
    )
    res1, got = _partitioned(
        mesh, part1, elem, origin, dest, weight, group, n_groups=1
    )
    r0 = int(np.asarray(res0.n_rounds)[0])
    r1 = int(np.asarray(res1.n_rounds)[0])
    assert r1 < r0, (r1, r0)
    g_flux = assemble_global_flux(part1, res1.flux)
    np.testing.assert_allclose(
        g_flux, np.asarray(ref.flux), rtol=0, atol=1e-12
    )
    np.testing.assert_allclose(
        got["track_length"], np.asarray(ref.track_length), atol=1e-12
    )


@pytest.mark.slow
def test_partitioned_halo_jittered_mesh_parity():
    """Halo parity on an IRREGULAR mesh (jittered interior vertices,
    near-degenerate tets): the robustness trio (entry-face mask with the
    canonical cross-cut back-reference, chase, bump) must agree with the
    single-chip walk through buffered guest elements too. f64, same
    arithmetic => exact agreement."""
    from test_jittered_mesh import _jittered_mesh

    mesh = _jittered_mesh(6, 0.25, seed=11, dtype=DTYPE)
    n = 256
    rng = np.random.default_rng(9)
    elem = rng.integers(0, mesh.ntet, n).astype(np.int32)
    origin = np.asarray(mesh.centroids())[elem]
    dest = rng.uniform(0.02, 0.98, (n, 3))
    weight = np.ones(n)
    group = np.zeros(n, np.int32)
    ref = _single_chip(mesh, elem, origin, dest, weight, group, n_groups=1)
    assert bool(np.asarray(ref.done).all())
    part = partition_mesh(mesh, N_DEV, halo_layers=2)
    res, got = _partitioned(
        mesh, part, elem, origin, dest, weight, group, n_groups=1
    )
    assert got["done"].all()
    assert int(np.sum(np.asarray(res.n_dropped))) == 0
    g_flux = assemble_global_flux(part, res.flux)
    np.testing.assert_allclose(
        g_flux, np.asarray(ref.flux), rtol=0, atol=1e-12
    )
    np.testing.assert_allclose(
        got["position"], np.asarray(ref.position), atol=1e-12
    )
    np.testing.assert_array_equal(
        got["material_id"], np.asarray(ref.material_id)
    )
    np.testing.assert_allclose(
        got["track_length"], np.asarray(ref.track_length), atol=1e-12
    )


@pytest.mark.parametrize("halo", [0, 1])
def test_partitioned_record_xpoints_matches_single_chip(box, halo):
    """Intersection-point recording on the partitioned walk: the buffers
    migrate with their particles, so each particle's recorded sequence is
    its full path order across chips — exactly the single-chip record
    (cut faces are interior faces, recorded once on the sending chip)."""
    part = partition_mesh(box, N_DEV, halo_layers=halo)
    elem, origin, dest, weight, group = _random_batch(box, 96, seed=3)
    K = 8
    ref = trace_impl(
        box,
        jnp.asarray(origin, DTYPE),
        jnp.asarray(dest, DTYPE),
        jnp.asarray(elem),
        jnp.ones(len(elem), bool),
        jnp.asarray(weight, DTYPE),
        jnp.asarray(group),
        jnp.full(len(elem), -1, jnp.int32),
        make_flux(box.ntet, 2, DTYPE),
        initial=False,
        max_crossings=box.ntet + 8,
        tolerance=1e-8,
        record_xpoints=K,
    )
    n = len(elem)
    dmesh = make_device_mesh(N_DEV)
    placed = distribute_particles(
        part, dmesh, elem,
        dict(
            origin=np.asarray(origin, np.float64),
            dest=np.asarray(dest, np.float64),
            weight=np.asarray(weight, np.float64),
            group=np.asarray(group, np.int32),
            material_id=np.full(n, -1, np.int32),
        ),
    )
    step = make_partitioned_step(
        dmesh, part, n_groups=2, max_crossings=box.ntet + 8,
        tolerance=1e-8, record_xpoints=K,
        compact_stages=((4, 64), (8, 32)),
    )
    from jax.sharding import NamedSharding, PartitionSpec as P

    flux = jax.device_put(
        jnp.zeros((N_DEV, part.max_local, 2, 2), DTYPE),
        NamedSharding(dmesh, P("p")),
    )
    res = step(
        placed["origin"].astype(DTYPE), placed["dest"].astype(DTYPE),
        placed["elem"], jnp.zeros_like(placed["valid"]),
        placed["material_id"], placed["weight"].astype(DTYPE),
        placed["group"], placed["particle_id"], placed["valid"], flux,
    )
    got = collect_by_particle_id(res, n)
    assert got["done"].all()
    np.testing.assert_array_equal(
        got["n_xpoints"], np.asarray(ref.n_xpoints)
    )
    np.testing.assert_allclose(
        got["xpoints"], np.asarray(ref.xpoints), atol=1e-12
    )
    # And the walk results are still exact alongside the recording.
    g_flux = assemble_global_flux(part, res.flux)
    np.testing.assert_allclose(
        g_flux, np.asarray(ref.flux), rtol=0, atol=1e-12
    )
    assert np.asarray(ref.n_xpoints).max() >= 2  # scenario non-trivial


def test_ledger_exact_in_f64_under_wrong_parent_relocation():
    """The conservation-ledger f32 drift discriminator, pinned (round 5).

    Sources deliberately start OUTSIDE their claimed parent element
    (~2 element sizes off), forcing long relocation chases that cross
    partition cuts before scoring begins. In f64 the migrated ledger
    must equal |final - source| within the walk's GEOMETRIC tolerance
    envelope (the escalated bump's unscored forward nudges are capped
    at tolerance=1e-8 per bumped crossing — measured max 4.5e-8 here,
    8 of 2048 lanes): any real cut-boundary double/missed scoring is a
    whole segment (~1e-2), while the known f32 drift (up to ~2.4e-3 at
    119 cells, BENCHMARKS.md 'Ledger f32 envelope at scale') is
    accumulation rounding. 1e-6 splits the three regimes cleanly."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    if not jax.config.jax_enable_x64:
        pytest.skip("f64 oracle needs x64")
    dtype = jnp.float64
    mesh = build_box(1.0, 1.0, 1.0, 10, 10, 10, dtype=dtype)
    part = partition_mesh(mesh, 8, halo_layers=1)
    dmesh = make_device_mesh(8)
    n, n_groups = 2048, 2
    rng = np.random.default_rng(11)
    cen = np.asarray(mesh.centroids())
    elem = rng.integers(0, mesh.ntet, n).astype(np.int32)
    src = np.clip(cen[elem] + rng.normal(0, 0.2, (n, 3)), 0.002, 0.998)
    u = rng.normal(size=(n, 3))
    u /= np.linalg.norm(u, axis=1, keepdims=True)
    dest = src + u * rng.exponential(0.4, (n, 1))
    step = make_partitioned_step(
        dmesh, part, n_groups=n_groups, max_crossings=mesh.ntet + 64,
        tolerance=1e-8,
    )
    placed = distribute_particles(
        part, dmesh, elem,
        dict(
            origin=src, dest=dest, weight=np.ones(n),
            group=rng.integers(0, n_groups, n).astype(np.int32),
            material_id=np.full(n, -1, np.int32),
        ),
    )
    flux = jax.device_put(
        jnp.zeros((8, part.max_local * n_groups * 2), dtype),
        NamedSharding(dmesh, P("p")),
    )
    res = step(
        placed["origin"].astype(dtype), placed["dest"].astype(dtype),
        placed["elem"], jnp.zeros_like(placed["valid"]),
        placed["material_id"], placed["weight"].astype(dtype),
        placed["group"], placed["particle_id"], placed["valid"], flux,
    )
    got = collect_by_particle_id(res, n)
    assert got["done"].all()
    assert int(np.asarray(res.n_dropped).sum()) == 0
    disp = np.linalg.norm(got["position"] - src, axis=1)
    np.testing.assert_allclose(got["track_length"], disp, atol=1e-6)
