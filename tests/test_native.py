"""Native C++ runtime vs. NumPy reference equivalence.

The native library (native/pumi_native.cpp) must produce bit-identical
derived tables and adjacency to the NumPy implementations it accelerates —
these tests pin that contract. They skip if the toolchain is unavailable
(the NumPy fallback path is what every other test exercises then).
"""
from __future__ import annotations

import textwrap

import numpy as np
import pytest

from pumiumtally_tpu import native
from pumiumtally_tpu.mesh import box
from pumiumtally_tpu.mesh.core import (
    _canonicalize_orientation,
    _face_planes,
    _tet_volumes,
)
from pumiumtally_tpu.mesh import io as mesh_io

pytestmark = pytest.mark.skipif(
    not native.available(), reason="native toolchain unavailable"
)


def _box_arrays(nx, ny, nz):
    coords, tets = box.build_box_arrays(1.0, 1.2, 0.8, nx, ny, nz)
    rng = np.random.default_rng(7)
    class_id = rng.integers(0, 3, tets.shape[0]).astype(np.int32)
    return np.asarray(coords, np.float64), np.asarray(tets, np.int64), class_id


def _numpy_tet2tet(tet2vert):
    """The pure-NumPy lexsort adjacency build (native dispatch bypassed)."""
    from pumiumtally_tpu.mesh.core import FACE_LOCAL_VERTS

    nt = tet2vert.shape[0]
    faces = tet2vert[:, FACE_LOCAL_VERTS]
    faces = np.sort(faces.reshape(nt * 4, 3), axis=1)
    owner = np.repeat(np.arange(nt, dtype=np.int64), 4)
    local = np.tile(np.arange(4, dtype=np.int64), nt)
    order = np.lexsort((faces[:, 2], faces[:, 1], faces[:, 0]))
    fs = faces[order]
    os_, ls = owner[order], local[order]
    t2t = np.full((nt, 4), -1, dtype=np.int64)
    same = np.all(fs[1:] == fs[:-1], axis=1)
    i = np.nonzero(same)[0]
    t2t[os_[i], ls[i]] = os_[i + 1]
    t2t[os_[i + 1], ls[i + 1]] = os_[i]
    return t2t


def test_tet2tet_matches_numpy():
    _, tets, _ = _box_arrays(5, 4, 3)
    got = native.build_tet2tet(tets)
    assert got is not None
    np.testing.assert_array_equal(got, _numpy_tet2tet(tets))


def test_derive_geometry_matches_numpy():
    coords, tets, _ = _box_arrays(4, 3, 5)
    # Scramble orientation so canonicalization has work to do.
    rng = np.random.default_rng(3)
    flip = rng.random(tets.shape[0]) < 0.5
    scrambled = tets.copy()
    scrambled[flip, 2], scrambled[flip, 3] = tets[flip, 3], tets[flip, 2]

    ref_t2v = _canonicalize_orientation(coords, scrambled.copy())
    ref_vol = _tet_volumes(coords, ref_t2v)
    ref_n, ref_d = _face_planes(coords, ref_t2v)

    out = native.derive_geometry(coords, scrambled.copy())
    assert out is not None
    t2v, vol, nrm, d = out
    np.testing.assert_array_equal(t2v, ref_t2v)
    np.testing.assert_allclose(vol, ref_vol, rtol=0, atol=1e-15)
    np.testing.assert_allclose(nrm, ref_n, rtol=0, atol=1e-14)
    np.testing.assert_allclose(d, ref_d, rtol=0, atol=1e-14)
    assert (vol > 0).all()


def test_gmsh_v2_native_matches_python(tmp_path):
    # One tet + one triangle (skipped) + physical tags, Gmsh v2.2 ASCII.
    msh = textwrap.dedent(
        """\
        $MeshFormat
        2.2 0 8
        $EndMeshFormat
        $Nodes
        5
        1 0 0 0
        2 1 0 0
        3 0 1 0
        4 0 0 1
        7 1 1 1
        $EndNodes
        $Elements
        3
        1 2 2 5 1 1 2 3
        2 4 2 9 1 1 2 3 4
        3 4 2 11 2 2 3 4 7
        $EndElements
        """
    )
    p = tmp_path / "two_tets.msh"
    p.write_text(msh)
    got = native.parse_gmsh(str(p))
    assert got is not None
    coords, tets, cids = got

    ref_coords, ref_tets, ref_cids = mesh_io._parse_gmsh_v2(
        p.read_text().split("\n")
    )
    np.testing.assert_allclose(coords, ref_coords)
    np.testing.assert_array_equal(tets, ref_tets)
    np.testing.assert_array_equal(cids, ref_cids)
    assert list(cids) == [9, 11]


def test_nonmanifold_raises():
    # Three tets sharing one face -> non-manifold; both the native build and
    # the NumPy fallback must refuse rather than emit a corrupt table.
    tets = np.array(
        [[0, 1, 2, 3], [0, 1, 2, 4], [0, 1, 2, 5]], dtype=np.int64
    )
    with pytest.raises(ValueError, match="non-manifold"):
        native.build_tet2tet(tets)
    with pytest.raises(ValueError, match="non-manifold"):
        _numpy_tet2tet_checked(tets)


def _numpy_tet2tet_checked(tets):
    """Route through the package function with native dispatch disabled via
    monkey-free indirection: call the module-level implementation after the
    native fast path (which raises first in the normal path)."""
    from unittest import mock

    from pumiumtally_tpu.mesh import core

    with mock.patch.object(native, "build_tet2tet", return_value=None):
        return core.build_tet2tet(tets)


def test_gmsh_skips_point_elements(tmp_path):
    # Physical-point (type 15) and line (type 1) elements are skipped, not
    # fatal — they appear in most real Gmsh exports.
    msh = textwrap.dedent(
        """\
        $MeshFormat
        2.2 0 8
        $EndMeshFormat
        $Nodes
        4
        1 0 0 0
        2 1 0 0
        3 0 1 0
        4 0 0 1
        $EndNodes
        $Elements
        3
        1 15 2 1 1 1
        2 1 2 3 1 1 2
        3 4 2 9 1 1 2 3 4
        $EndElements
        """
    )
    p = tmp_path / "with_points.msh"
    p.write_text(msh)
    got = native.parse_gmsh(str(p))
    assert got is not None
    coords, tets, cids = got
    assert tets.shape == (1, 4)
    assert list(cids) == [9]


def test_gmsh_v41_native_matches_python(tmp_path):
    # Two node blocks, a triangle block (skipped) and two tet blocks with
    # distinct entity tags, Gmsh v4.1 ASCII.
    msh = textwrap.dedent(
        """\
        $MeshFormat
        4.1 0 8
        $EndMeshFormat
        $Nodes
        2 5 1 7
        3 1 0 3
        1
        2
        3
        0 0 0
        1 0 0
        0 1 0
        3 2 0 2
        4
        7
        0 0 1
        1 1 1
        $EndNodes
        $Elements
        3 3 1 3
        2 5 2 1
        1 1 2 3
        3 9 4 1
        2 1 2 3 4
        3 11 4 1
        3 2 3 4 7
        $EndElements
        """
    )
    p = tmp_path / "two_tets_v41.msh"
    p.write_text(msh)
    got = native.parse_gmsh(str(p))
    assert got is not None, "native v4.1 tokenizer should handle this file"
    coords, tets, cids = got

    ref_coords, ref_tets, ref_cids = mesh_io._parse_gmsh_v4(
        p.read_text().split("\n")
    )
    np.testing.assert_allclose(coords, ref_coords)
    np.testing.assert_array_equal(tets, ref_tets)
    np.testing.assert_array_equal(cids, ref_cids)
    assert list(cids) == [9, 11]
