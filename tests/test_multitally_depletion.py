"""Reaction-rate multi-tally + depletion loop.

The reaction-rate identity is exact: because the response depends only on
(element region, group), the post-hoc product must equal what an in-loop
σ-weighted scatter would have accumulated — tested against a hand-built
per-element recomputation. The depletion loop is tested for its workflow
invariants (densities fall monotonically, rates stay consistent).
"""
from __future__ import annotations

import numpy as np
import pytest

from pumiumtally_tpu import PumiTally, TallyConfig
from pumiumtally_tpu.mesh.box import build_box_arrays
from pumiumtally_tpu.mesh.core import TetMesh
from pumiumtally_tpu.models.depletion import DepletionLoop, RegionNuclide


def _two_region(cells=3):
    coords, tets = build_box_arrays(1.0, 1.0, 1.0, cells, cells, cells)
    cid = (coords[tets].mean(axis=1)[:, 0] > 0.5).astype(np.int32)
    return TetMesh.from_numpy(coords, tets, cid)


def _driven_tally(n=48, n_groups=3, moves=4, seed=0):
    mesh = _two_region()
    t = PumiTally(mesh, n, TallyConfig(n_groups=n_groups, tolerance=1e-6))
    rng = np.random.default_rng(seed)
    t.initialize_particle_location(rng.uniform(0.1, 0.9, (n, 3)).ravel())
    for _ in range(moves):
        dest = rng.uniform(0.05, 0.95, (n, 3))
        t.move_to_next_location(
            dest, np.ones(n, np.int8),
            rng.uniform(0.5, 2.0, n),
            rng.integers(0, n_groups, n).astype(np.int32),
            np.full(n, -1, np.int32),
        )
    return t


def test_reaction_rate_identity():
    t = _driven_tally()
    sigma = np.array([[0.5, 1.0, 2.0], [3.0, 0.25, 0.0]])
    rr = t.reaction_rate(sigma)
    flux = t.raw_flux
    cid = np.asarray(t.mesh.class_id)
    expect0 = flux[..., 0] * sigma[cid]
    expect1 = flux[..., 1] * sigma[cid] ** 2
    np.testing.assert_allclose(rr[..., 0], expect0, rtol=1e-6)
    np.testing.assert_allclose(rr[..., 1], expect1, rtol=1e-6)


def test_reaction_rate_out_of_range_region_scores_zero():
    t = _driven_tally()
    sigma = np.array([[1.0, 1.0, 1.0]])  # only region 0 covered
    rr = t.reaction_rate(sigma)
    cid = np.asarray(t.mesh.class_id)
    assert np.all(rr[cid == 1] == 0.0)
    assert rr[cid == 0, :, 0].sum() > 0


@pytest.mark.slow
def test_depletion_burns_density_down():
    mesh = _two_region()
    t = PumiTally(mesh, 64, TallyConfig(n_groups=2, tolerance=1e-6))
    inv = {
        0: RegionNuclide(density=1.0, micro_total=3.0, micro_absorption=1.5),
        1: RegionNuclide(density=2.0, micro_total=5.0, micro_absorption=2.0),
    }
    loop = DepletionLoop(t, inv, dt=0.05, seed=7)
    hist = loop.run(3)
    assert len(hist) == 3
    for rid in (0, 1):
        dens = [h.densities[rid] for h in hist]
        assert all(d2 < d1 for d1, d2 in zip(dens, dens[1:])), dens
        assert all(h.absorption_rate[rid] > 0 for h in hist)
    assert all(h.total_flux > 0 for h in hist)


@pytest.mark.slow
def test_partitioned_depletion_rehearsal(monkeypatch):
    """Config-5 shape over the PARTITIONED walk (BASELINE ladder #5
    template for the partition-mandatory 100M-tet scale): N depletion
    steps on the 8-way virtual mesh with a compiled-once step, conserved
    migrated ledgers, zero drops, and physically ordered burn."""
    import os

    monkeypatch.syspath_prepend(
        os.path.join(os.path.dirname(__file__), os.pardir, "scripts")
    )
    from depletion_partitioned import run_rehearsal

    rec = run_rehearsal(cells=5, n=1024, n_steps=2)
    assert rec["ok"], rec
    for s in rec["steps"]:
        assert s["ledger_ok"] and s["all_done"] and s["n_dropped"] == 0
    assert rec["burn_monotone"] and rec["inner_burns_faster"]
