"""The flat flux device layout must be a pure re-indexing of the 3-D one.

On TPU the canonical [ntet, n_groups, 2] accumulator pads its minor dim
2 → 128 under the (8,128) tile layout — a 64× HBM blowup (the 1M-tet
64-group flux allocated 32.7 GB, round-4 capture bench_v3b_64g). The
production paths therefore keep the accumulator FLAT on device
(make_flux flat=True + trace_impl n_groups=...) and assemble the 3-D
view host-side. These tests pin that the flat path is bit-identical to
the 3-D path, and that the host-side normalize/reaction-rate twins match
their jitted originals.
"""
from __future__ import annotations

import numpy as np

import jax.numpy as jnp

from pumiumtally_tpu import build_box, make_flux
from pumiumtally_tpu.core.tally import (
    normalize_flux,
    normalize_flux_host,
    reaction_rate,
    reaction_rate_host,
)
from pumiumtally_tpu.ops.walk import trace_impl


def _scene(n=128, n_groups=3, seed=3):
    mesh = build_box(1.0, 1.0, 1.0, 4, 4, 4, dtype=jnp.float32)
    rng = np.random.default_rng(seed)
    elem = jnp.asarray(rng.integers(0, mesh.ntet, n).astype(np.int32))
    origin = jnp.asarray(
        np.asarray(mesh.centroids())[np.asarray(elem)], jnp.float32
    )
    dest = jnp.asarray(rng.uniform(-0.1, 1.1, (n, 3)), jnp.float32)
    args = (
        mesh, origin, dest, elem,
        jnp.ones(n, bool),
        jnp.asarray(rng.uniform(0.5, 2.0, n), jnp.float32),
        jnp.asarray(rng.integers(0, n_groups, n), jnp.int32),
        jnp.full(n, -1, jnp.int32),
    )
    kw = dict(initial=False, max_crossings=mesh.ntet + 8, tolerance=1e-6)
    return mesh, args, kw, n_groups


def test_flat_flux_matches_3d():
    mesh, args, kw, g = _scene()
    r3 = trace_impl(*args, make_flux(mesh.ntet, g, jnp.float32), **kw)
    rf = trace_impl(
        *args, make_flux(mesh.ntet, g, jnp.float32, flat=True),
        n_groups=g, **kw,
    )
    assert rf.flux.shape == (mesh.ntet * g * 2,)
    np.testing.assert_array_equal(
        np.asarray(rf.flux).reshape(mesh.ntet, g, 2), np.asarray(r3.flux)
    )
    np.testing.assert_array_equal(np.asarray(rf.elem), np.asarray(r3.elem))
    np.testing.assert_array_equal(
        np.asarray(rf.position), np.asarray(r3.position)
    )
    assert int(rf.n_segments) == int(r3.n_segments)


def test_flat_flux_requires_n_groups():
    mesh, args, kw, g = _scene(n=8)
    flat = make_flux(mesh.ntet, g, jnp.float32, flat=True)
    try:
        trace_impl(*args, flat, **kw)
    except ValueError as e:
        assert "n_groups" in str(e)
    else:  # pragma: no cover
        raise AssertionError("flat flux without n_groups must raise")


def test_flat_flux_64_groups():
    """Config-4 regime guard (64 energy groups): the flat stride-2 keys
    (elem*64 + group)*2 must stay exact at high group counts, and the
    accumulator must conserve track length across groups. (On TPU this
    shape OOMed as 3-D — 32.7 GB padded — which is why flat is the
    production layout; here the math is pinned at CPU scale.)"""
    g = 64
    mesh, args, kw, _ = _scene(n=256, n_groups=g, seed=9)
    r = trace_impl(
        *args, make_flux(mesh.ntet, g, jnp.float32, flat=True),
        n_groups=g, **kw,
    )
    flux = np.asarray(r.flux).reshape(mesh.ntet, g, 2)
    # Every group index used must have landed in its own bin: total Σc
    # equals the weighted ledger, and per-group totals are nonzero for
    # every group the batch used.
    w = np.asarray(args[5])
    tl = np.asarray(r.track_length)
    np.testing.assert_allclose(
        flux[..., 0].sum(), (w * tl).sum(), rtol=1e-5
    )
    used = np.unique(np.asarray(args[6]))
    per_group = flux[..., 0].sum(axis=0)
    assert (per_group[used] > 0).all()
    unused = np.setdiff1d(np.arange(g), used)
    assert (per_group[unused] == 0).all()


def test_normalize_flux_host_matches_device():
    mesh, args, kw, g = _scene()
    r = trace_impl(*args, make_flux(mesh.ntet, g, jnp.float32), **kw)
    flux = np.asarray(r.flux)
    vols = np.asarray(mesh.volumes)
    dev = np.asarray(normalize_flux(r.flux, mesh.volumes, 128, 4))
    host = normalize_flux_host(flux, vols, 128, 4)
    np.testing.assert_allclose(host, dev, rtol=1e-6, atol=0)


def test_reaction_rate_host_matches_device():
    mesh, args, kw, g = _scene()
    r = trace_impl(*args, make_flux(mesh.ntet, g, jnp.float32), **kw)
    rng = np.random.default_rng(0)
    sigma = rng.uniform(0.1, 2.0, (3, g)).astype(np.float32)
    dev = np.asarray(
        reaction_rate(r.flux, mesh.class_id, jnp.asarray(sigma))
    )
    host = reaction_rate_host(
        np.asarray(r.flux), np.asarray(mesh.class_id), sigma
    )
    np.testing.assert_allclose(host, dev, rtol=1e-6, atol=0)
