"""Port of the reference's white-box integration test to the TPU-native
framework: same 6-tet unit box, same 5 particles, same rays, same expected
fluxes at 1e-8 (test_pumi_tally_impl_methods.cpp:31-401). This is the
minimum end-to-end acceptance gate (SURVEY.md §7 stage 5)."""
import jax.numpy as jnp
import numpy as np
import pytest

from pumiumtally_tpu import PumiTally, TallyConfig, build_box

NUM = 5
TOL = 1e-8


@pytest.fixture()
def tally():
    mesh = build_box(dtype=jnp.float64)
    return PumiTally(mesh, NUM, TallyConfig(dtype=jnp.float64))


def _init(tally):
    pos = np.tile([0.1, 0.4, 0.5], NUM)
    tally.initialize_particle_location(pos, pos.size)
    return tally


def _move1(tally):
    dest = np.tile([1.2, 0.4, 0.5], NUM)
    flying = np.ones(NUM, dtype=np.int8)
    weights = np.ones(NUM)
    groups = np.zeros(NUM, dtype=np.int32)
    mats = np.zeros(NUM, dtype=np.int32)
    tally.move_to_next_location(dest, flying, weights, groups, mats, dest.size)
    return dest, flying, mats


def test_ctor_invariants(tally):
    # Buffer/particle-structure invariants (test:60-80).
    assert tally.state.capacity == NUM
    assert tally.mesh.ntet == 6
    assert tally.raw_flux.shape == (6, 2, 2)
    # All particles seeded at elem 0's centroid (test:82-110).
    origins = np.asarray(tally.state.origin)
    np.testing.assert_allclose(
        origins, np.tile([0.5, 0.75, 0.25], (NUM, 1)), atol=TOL
    )
    np.testing.assert_array_equal(tally.element_ids, 0)


def test_initial_search_lands_in_elem2_without_tallying(tally):
    _init(tally)
    # All particles reached element 2 (test:152-159).
    np.testing.assert_array_equal(tally.element_ids, 2)
    # Initial search must not tally (test:161-170).
    np.testing.assert_allclose(tally.raw_flux, 0.0, atol=TOL)
    # Particles now sit at their source positions.
    np.testing.assert_allclose(
        np.asarray(tally.state.origin),
        np.tile([0.1, 0.4, 0.5], (NUM, 1)),
        atol=TOL,
    )


def test_move_crosses_2_3_4_and_clips_at_domain_boundary(tally):
    _init(tally)
    dest, flying, mats = _move1(tally)

    # Particles stop in element 4 (test:224-231).
    np.testing.assert_array_equal(tally.element_ids, 4)
    # Destination clipped to the x=1 domain face (test:233-254 and its
    # in-source fixme: the new position must be 1.0, not 1.1/1.2).
    np.testing.assert_allclose(
        dest.reshape(NUM, 3), np.tile([1.0, 0.4, 0.5], (NUM, 1)), atol=TOL
    )
    # Host flying flags reset to 0 (test:203-212 / cpp:316-319).
    np.testing.assert_array_equal(flying, 0)
    # Domain exit reports material -1 (cpp:480-482).
    np.testing.assert_array_equal(mats, -1)

    # Segment lengths 0.3 / 0.1 / 0.5 in elements 2 / 3 / 4, ×5 particles
    # (test:270-286).
    flux = tally.raw_flux
    expected = np.zeros(6)
    expected[2], expected[3], expected[4] = 0.3 * NUM, 0.1 * NUM, 0.5 * NUM
    np.testing.assert_allclose(flux[:, 0, 0], expected, atol=TOL)
    # Untouched group stays zero.
    np.testing.assert_allclose(flux[:, 1, :], 0.0, atol=TOL)
    # Squared-contribution slot accumulates per-segment (w·len)^2
    # (cpp:640-642).
    expected_sq = np.zeros(6)
    expected_sq[2], expected_sq[3], expected_sq[4] = (
        0.09 * NUM,
        0.01 * NUM,
        0.25 * NUM,
    )
    np.testing.assert_allclose(flux[:, 0, 1], expected_sq, atol=TOL)


def test_second_move_accumulates_heterogeneous_weights(tally):
    _init(tally)
    _move1(tally)

    # Particles 0 and 2 fly from (1.0, 0.4, 0.5) with weights 2.0 and 0.5;
    # the rest are parked (test:288-326).
    dest = np.tile([1.0, 0.4, 0.5], (NUM, 1))
    dest[0] = [0.15, 0.05, 0.20]
    dest[2] = [0.85, 0.05, 0.10]
    flying = np.zeros(NUM, dtype=np.int8)
    flying[0] = flying[2] = 1
    weights = np.ones(NUM)
    weights[0], weights[2] = 2.0, 0.5
    groups = np.zeros(NUM, dtype=np.int32)
    mats = np.zeros(NUM, dtype=np.int32)
    flat = dest.reshape(-1).copy()
    tally.move_to_next_location(flat, flying, weights, groups, mats, flat.size)

    # New origins equal the requested destinations (test:329-352).
    np.testing.assert_allclose(flat.reshape(NUM, 3), dest, atol=TOL)
    # Parent elements {3, 4, 4, 4, 4} (test:354-366).
    np.testing.assert_array_equal(tally.element_ids, [3, 4, 4, 4, 4])

    # Flux accumulation against the reference's hand-computed segments
    # (test:368-399): particle 0 contributes 0.8790… in 4 and 0.0879… in 3;
    # particle 2 contributes 0.5522… in 4.
    flux = tally.raw_flux
    expected = np.zeros(6)
    expected[2] = 0.3 * NUM
    expected[3] = 0.1 * NUM + 0.08790490988459178 * 2.0
    expected[4] = (
        0.5 * NUM + 0.879049070406094 * 2.0 + 0.552268050859363 * 0.5
    )
    np.testing.assert_allclose(flux[:, 0, 0], expected, atol=TOL)


def test_normalization_and_vtk(tally, tmp_path):
    _init(tally)
    _move1(tally)
    norm = tally.normalized_flux()
    # Volume normalization: flux / (vol * N) with vol = 1/6 (cpp:660-677).
    vol = 1.0 / 6.0
    assert norm[2, 0, 0] == pytest.approx(0.3 * NUM / (vol * NUM), abs=TOL)
    assert norm[4, 0, 0] == pytest.approx(0.5 * NUM / (vol * NUM), abs=TOL)
    # sd slot is finite (the reference's formula NaNs, flagged in-code at
    # cpp:673-677; ours is guarded).
    assert np.isfinite(norm[..., 2]).all()

    out = tally.write_pumi_tally_mesh(str(tmp_path / "fluxresult.vtu"))
    text = open(out).read()
    assert "flux_group_0" in text and "flux_group_1" in text
    assert "volume" in text


def test_parked_particles_keep_position_and_material(tally):
    _init(tally)
    _move1(tally)
    # All parked: nothing moves, nothing tallies.
    before = tally.raw_flux.copy()
    dest = np.tile([0.5, 0.5, 0.5], NUM)  # ignored for parked particles
    flying = np.zeros(NUM, dtype=np.int8)
    mats = np.full(NUM, 7, dtype=np.int32)
    tally.move_to_next_location(
        dest, flying, np.ones(NUM), np.zeros(NUM, np.int32), mats, dest.size
    )
    np.testing.assert_allclose(
        dest.reshape(NUM, 3), np.tile([1.0, 0.4, 0.5], (NUM, 1)), atol=TOL
    )
    np.testing.assert_array_equal(tally.element_ids, 4)
    np.testing.assert_allclose(tally.raw_flux, before, atol=TOL)


def test_sd_matches_analytic_variance():
    """Analytic MC-variance oracle for the sd slot (round-2 VERDICT item 8).

    Model: N particles each make M moves; in one tet of volume V every
    (particle, move) scores y = w·L with fixed segment length L and
    weights drawn from a known-variance distribution. The flux estimate
    is Σy/(V·N) with variance M·Var(y)/(N·V²), so

        sd_true ≈ L·sqrt(M·Var(w)/N) / V.

    The raw accumulator (Σc, Σc²) is built directly from the samples, so
    the test isolates the normalization math from the walk. The exact
    finite-sample identity sd = sqrt(M·s²_y/N)/V must hold to rounding,
    and the analytic value within sampling error. The reference's
    formula sqrt(m2 − m1²) (its own FIXME, cpp:673-677) fails both — it
    is off by ~sqrt(N/M)·... a factor growing with N — which this test
    demonstrates explicitly.
    """
    import jax.numpy as jnp

    from pumiumtally_tpu.core.tally import normalize_flux

    rng = np.random.default_rng(123)
    N, M = 40_000, 7
    L, V = 0.25, 1.0 / 6.0
    w = rng.uniform(0.5, 1.5, (N, M))  # Var(w) = 1/12
    y = (w * L).reshape(-1)
    flux = np.zeros((1, 1, 2))
    flux[0, 0, 0] = y.sum()
    flux[0, 0, 1] = (y * y).sum()

    norm = np.asarray(
        normalize_flux(
            jnp.asarray(flux), jnp.asarray([V]), N, M
        )
    )
    got_sd = norm[0, 0, 2]

    # Exact finite-sample identity.
    h = N * M
    s2y = (y * y).sum() - y.sum() ** 2 / h
    s2y /= h - 1
    sd_exact = np.sqrt(M * s2y / N) / V
    assert got_sd == pytest.approx(sd_exact, rel=1e-6)

    # Analytic convergence: Var(w)=1/12 ⇒ sd_true = L·sqrt(M/(12N))/V.
    sd_true = L * np.sqrt(M / (12 * N)) / V
    assert got_sd == pytest.approx(sd_true, rel=0.05)

    # The reference's broken formula (cpp:673-677) fails outright: its
    # m2 − m1² goes negative under multi-move accumulation (m1 grows
    # with M, m2 doesn't), so its sqrt is NaN — the very failure its
    # in-code FIXME flags.
    m1 = flux[0, 0, 0] / (V * N)
    m2 = flux[0, 0, 1] / (V * V * N)
    assert m2 - m1 * m1 < 0
    assert np.isnan(np.sqrt(m2 - m1 * m1))

    # Mean parity is untouched: E[flux] = M·E[w]·L/V.
    assert norm[0, 0, 0] == pytest.approx(M * 1.0 * L / V, rel=0.01)


def test_intersection_points_surface():
    """getIntersectionPoints() parity behind TallyConfig.record_xpoints
    (reference test_pumi_tally_impl_methods.cpp:403-479): the oracle ray
    (0.1,0.4,0.5)→(1.2,0.4,0.5) crosses faces at x=0.4 and x=0.5 and is
    clipped at the x=1 wall, so each particle records exactly those three
    points in order."""
    mesh = build_box(dtype=jnp.float64)
    tally = PumiTally(
        mesh, NUM, TallyConfig(dtype=jnp.float64, record_xpoints=8)
    )
    _init(tally)
    _move1(tally)
    xp, counts = tally.intersection_points()
    assert xp.shape == (NUM, 8, 3)
    np.testing.assert_array_equal(counts, 3)
    expected = np.array(
        [[0.4, 0.4, 0.5], [0.5, 0.4, 0.5], [1.0, 0.4, 0.5]]
    )
    for i in range(NUM):
        np.testing.assert_allclose(xp[i, :3], expected, atol=TOL)
    # Flag off → the surface is explicitly unavailable, and the hot path
    # carries no buffer.
    t2 = PumiTally(mesh, NUM, TallyConfig(dtype=jnp.float64))
    with pytest.raises(ValueError, match="record_xpoints"):
        t2.intersection_points()


@pytest.mark.slow
def test_intersection_points_no_crossing_and_pre_trace_errors():
    """A particle that never leaves its tet records ZERO crossing points
    (the recorder logs genuine boundary crossings only), and calling the
    surface before any trace raises a clear error."""
    mesh = build_box(dtype=jnp.float64)
    t = PumiTally(
        mesh, NUM, TallyConfig(dtype=jnp.float64, record_xpoints=4)
    )
    with pytest.raises(RuntimeError, match="no trace has run"):
        t.intersection_points()
    _init(t)
    # Tiny in-element hop: start (0.1,0.4,0.5) in elem 2, move 1e-3 in x.
    dest = np.tile([0.101, 0.4, 0.5], NUM)
    flying = np.ones(NUM, np.int8)
    t.move_to_next_location(
        dest, flying, np.ones(NUM), np.zeros(NUM, np.int32),
        np.zeros(NUM, np.int32), dest.size,
    )
    _, counts = t.intersection_points()
    np.testing.assert_array_equal(counts, 0)


def test_batch_sd_matches_analytic_variance():
    """The cheap-tally sd (TallyConfig sd_mode="batch") against the SAME
    analytic oracle as the segment estimator (VERDICT r4 item 2a).

    Same model: N particles x M moves, per-(particle, move) score
    y = w·L in one tet. Batch mode accumulates T_m = Σ_particles y (the
    per-move bin total) and Σ T_m² — what accumulate_batch_squares
    builds from per-move deltas — and normalize_flux(sd_mode="batch")
    must (1) satisfy its finite-sample identity exactly, (2) converge
    to the same analytic sd_true = L·sqrt(M·Var(w)/N)/V (the estimand
    is identical for independent particle scores), and (3) pay the
    predicted statistical price: the estimator has M−1 degrees of
    freedom instead of N·M−1.
    """
    import jax.numpy as jnp

    from pumiumtally_tpu.core.tally import normalize_flux

    rng = np.random.default_rng(321)
    N, M = 40_000, 64
    L, V = 0.25, 1.0 / 6.0
    w = rng.uniform(0.5, 1.5, (N, M))  # Var(w) = 1/12
    t = (w * L).sum(axis=0)  # per-move bin totals, shape [M]
    flux = np.zeros((1, 1, 2))
    flux[0, 0, 0] = t.sum()
    flux[0, 0, 1] = (t * t).sum()

    norm = np.asarray(
        normalize_flux(
            jnp.asarray(flux), jnp.asarray([V]), N, M, sd_mode="batch"
        )
    )
    got_sd = norm[0, 0, 2]

    # Exact finite-sample identity: sd = sqrt(M·s²_T)/(V·N).
    s2t = ((t * t).sum() - t.sum() ** 2 / M) / (M - 1)
    sd_exact = np.sqrt(M * s2t) / (V * N)
    assert got_sd == pytest.approx(sd_exact, rel=1e-6)

    # Same estimand as segment mode: sd_true = L·sqrt(M/(12N))/V.
    # Tolerance is the estimator's own noise: relative sd-of-sd
    # ~ 1/sqrt(2(M−1)) ≈ 9% at M=64 (the quantified cost of the
    # cheap mode; segment mode at the same workload sits at
    # 1/sqrt(2(NM−1)) ≈ 0.04%).
    sd_true = L * np.sqrt(M / (12 * N)) / V
    assert got_sd == pytest.approx(sd_true, rel=4 / np.sqrt(2 * (M - 1)))

    # Mean is untouched by the mode.
    assert norm[0, 0, 0] == pytest.approx(M * 1.0 * L / V, rel=0.01)


def test_batch_sd_mode_through_facade():
    """sd_mode="batch" end-to-end: same mean flux bit-for-bit as
    segment mode, squares accumulated per move, sd within the batch
    estimator's noise of the segment sd."""
    import jax.numpy as jnp

    from pumiumtally_tpu import build_box
    from pumiumtally_tpu.api import PumiTally, TallyConfig

    mesh = build_box(1.0, 1.0, 1.0, 4, 4, 4, dtype=jnp.float64)
    cents = np.asarray(mesh.centroids())
    N, M = 2048, 6
    runs = {}
    for mode in ("segment", "batch"):
        t = PumiTally(
            mesh, N,
            TallyConfig(dtype=jnp.float64, n_groups=2, sd_mode=mode),
        )
        rng = np.random.default_rng(7)
        elem = rng.integers(0, mesh.ntet, N).astype(np.int32)
        pos = cents[elem].astype(np.float64)
        t.initialize_particle_location(pos.reshape(-1).copy())
        prev = pos.copy()
        for _ in range(M):
            d = rng.normal(0, 1, (N, 3))
            d /= np.linalg.norm(d, axis=1, keepdims=True)
            ln = rng.exponential(0.2, (N, 1))
            buf = np.clip(prev + d * ln, 0.01, 0.99).reshape(-1).copy()
            fly = np.ones(N, np.int8)
            t.move_to_next_location(
                buf, fly, np.ones(N),
                rng.integers(0, 2, N).astype(np.int32),
                np.full(N, -1, np.int32),
            )
            prev = buf.reshape(N, 3)
        runs[mode] = (t.raw_flux.copy(), t.normalized_flux())

    seg_raw, seg_norm = runs["segment"]
    bat_raw, bat_norm = runs["batch"]
    # Identical walk, identical mean accumulator.
    np.testing.assert_array_equal(seg_raw[..., 0], bat_raw[..., 0])
    np.testing.assert_array_equal(seg_norm[..., 0], bat_norm[..., 0])
    # Squares slots hold different statistics (ΣT² vs Σc²) by design.
    assert not np.array_equal(seg_raw[..., 1], bat_raw[..., 1])
    # The sds estimate the same quantity: compare in aggregate over
    # well-sampled bins (batch has only M-1=5 dof per bin, so compare
    # the distribution center, not bin-by-bin).
    mask = seg_raw[..., 0] > np.percentile(seg_raw[..., 0], 90)
    ratio = bat_norm[..., 2][mask] / seg_norm[..., 2][mask]
    assert 0.5 < np.median(ratio) < 2.0, np.median(ratio)
