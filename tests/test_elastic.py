"""Elastic fault tolerance (ISSUE 12): sharded two-phase checkpoint
generations, coordinated rollback under the failure taxonomy, and
mesh-shrink recovery for the partitioned facade.

Acceptance contract: a ``chip_down_at_move:K`` injected into a
partitioned run triggers automatic rollback + re-partition onto the
surviving devices and the completed run's flux matches a fault-free
run at the shrunk part count (bitwise for same-layout rollback,
physics-equal via the layout-independence oracle for the shrink);
torn-shard generations are rejected ATOMICALLY (manifest missing or
any shard digest bad → the whole generation is skipped and an older
one restored).
"""
from __future__ import annotations

import json
import os
import subprocess
import sys

import numpy as np
import pytest

import jax.numpy as jnp

from pumiumtally_tpu import TallyConfig
from pumiumtally_tpu.mesh.box import build_box_arrays
from pumiumtally_tpu.mesh.core import TetMesh
from pumiumtally_tpu.parallel.partitioned_api import PartitionedTally
from pumiumtally_tpu.resilience import (
    ChaosInjector,
    CheckpointStore,
    ChipLostError,
    FaultInjector,
    FaultPlan,
    InjectedPreemption,
    ResilientRunner,
    chaos_plan,
    parse_faults,
)
from pumiumtally_tpu.utils.checkpoint import (
    MANIFEST_NAME,
    CheckpointIntegrityError,
    verify_checkpoint,
)

N = 16


@pytest.fixture(scope="module")
def mesh():
    coords, t2v = build_box_arrays(1.0, 1.0, 1.0, 4, 4, 4)
    cen = coords[t2v].mean(axis=1)
    cls = np.where(cen[:, 0] < 0.5, 1, 2).astype(np.int32)
    return TetMesh.from_numpy(coords, t2v, class_id=cls, dtype=jnp.float64)


CFG = dict(n_groups=2, dtype=jnp.float64, tolerance=1e-8)


def _inputs(i):
    """Deterministic per-move inputs (replayable across processes and
    layouts — pid order, not slot order)."""
    rng = np.random.default_rng(100 + i)
    return (
        rng.uniform(0.05, 0.95, (N, 3)).ravel().copy(),
        np.ones(N, np.int8),
        rng.uniform(0.5, 2.0, N),
        rng.integers(0, 2, N).astype(np.int32),
        np.full(N, -1, np.int32),
    )


def _pos():
    return np.random.default_rng(42).uniform(0.1, 0.9, (N, 3)).ravel()


def _reference(mesh, n_parts, moves):
    t = PartitionedTally(mesh, N, TallyConfig(**CFG), n_parts=n_parts)
    t.initialize_particle_location(_pos())
    for i in range(1, moves + 1):
        t.move_to_next_location(*_inputs(i))
    return t


# ===================================================================== #
# Sharded two-phase generations
# ===================================================================== #
def test_sharded_generation_layout_and_roundtrip(mesh, tmp_path):
    """A partitioned store generation is a directory of one npz per
    mesh part plus a MANIFEST.json naming every shard's digest; the
    restore is exact, under the SAME or a DIFFERENT layout (the
    payload split is layout-independent)."""
    t = _reference(mesh, 8, 2)
    store = CheckpointStore(str(tmp_path / "cks"))
    path = store.save(t)
    assert path.endswith(".shards") and os.path.isdir(path)
    assert store.last_shards == 8
    shards = sorted(
        n for n in os.listdir(path) if n.startswith("shard-")
    )
    assert len(shards) == 8
    manifest = json.loads(
        (tmp_path / "cks" / os.path.basename(path) / MANIFEST_NAME)
        .read_text()
    )
    assert set(manifest["shards"]) == set(shards)
    assert manifest["meta"]["iter_count"] == 2
    assert verify_checkpoint(path)["iter_count"] == 2

    # Same-layout restore: exact.
    b = PartitionedTally(mesh, N, TallyConfig(**CFG), n_parts=8)
    assert store.restore_latest(b) == 2
    np.testing.assert_allclose(b.raw_flux, t.raw_flux, rtol=0, atol=0)
    np.testing.assert_array_equal(b.elem_global, t.elem_global)

    # Cross-layout restore (the elastic lever): exact flux, continued
    # accumulation physics-equal.
    c = PartitionedTally(mesh, N, TallyConfig(**CFG), n_parts=4)
    assert store.restore_latest(c) == 2
    np.testing.assert_allclose(c.raw_flux, t.raw_flux, rtol=0, atol=0)


def test_torn_shard_rejected_atomically(mesh, tmp_path):
    """Any bad shard digest rejects the WHOLE generation (no
    Frankenstein restore mixing shard vintages) and falls back to the
    previous one; a missing manifest (crash between the two commit
    phases) is equally fatal to the generation."""
    store = CheckpointStore(str(tmp_path / "cks"), keep=4)
    t = _reference(mesh, 8, 0)
    store.save(t)
    for i in (1, 2):
        t.move_to_next_location(*_inputs(i))
        store.save(t)
    assert store.find_latest()[0] == 2

    # Tear one shard of the newest generation: digest mismatch.
    newest = store.shard_dir_for(2)
    target = os.path.join(newest, "shard-003.npz")
    with open(target, "r+b") as f:
        f.truncate(os.path.getsize(target) // 2)
    with pytest.raises(CheckpointIntegrityError, match="sha256"):
        verify_checkpoint(newest)
    assert store.find_latest()[0] == 1

    # Un-commit the next generation: manifest missing.
    os.unlink(os.path.join(store.shard_dir_for(1), MANIFEST_NAME))
    assert store.find_latest()[0] == 0
    b = PartitionedTally(mesh, N, TallyConfig(**CFG), n_parts=8)
    assert store.restore_latest(b) == 0
    assert b.iter_count == 0


def test_torn_shard_fault_through_runner(mesh, tmp_path):
    """The ``torn_shard:G`` injected mode tears the G-th generation the
    supervisor writes; resume must skip it and restore the previous
    generation, then replay to the same final state."""
    ref = _reference(mesh, 8, 3)

    d = str(tmp_path / "cks")
    t = PartitionedTally(mesh, N, TallyConfig(**CFG), n_parts=8)
    run = ResilientRunner(
        t, d, every_moves=1, handle_signals=False,
        sleep=lambda s: None,
        faults=FaultInjector(parse_faults("torn_shard:4")),
    )
    run.initialize_particle_location(_pos())
    for i in range(1, 4):
        run.move_to_next_location(*_inputs(i))
    # Generation 4 (= iteration 3) is torn: newest valid is iter 2.
    assert run.store.find_latest()[0] == 2
    assert t.metrics.counter(
        "pumi_injected_faults_total"
    ).value(kind="torn_shard") == 1

    b = PartitionedTally(mesh, N, TallyConfig(**CFG), n_parts=8)
    run_b = ResilientRunner(b, d, every_moves=1, handle_signals=False)
    assert run_b.resumed_from == 2
    for i in range(1, 4):
        if b.iter_count >= i:
            continue
        run_b.move_to_next_location(*_inputs(i))
    np.testing.assert_allclose(
        b.raw_flux, ref.raw_flux, rtol=0, atol=1e-12
    )


def test_single_file_generations_stay_compatible(mesh, tmp_path):
    """``shards=None`` keeps the pre-sharding single-file layout, and
    the two layouts interleave in one store history."""
    t = _reference(mesh, 8, 1)
    store = CheckpointStore(str(tmp_path / "cks"), shards=None)
    path = store.save(t)
    assert path.endswith(".npz") and os.path.isfile(path)
    assert store.last_shards == 0
    # A sharded generation lands beside it; both resolve.
    t.move_to_next_location(*_inputs(2))
    sharded = CheckpointStore(str(tmp_path / "cks"))  # default auto
    assert sharded.save(t).endswith(".shards")
    assert [it for it, _ in sharded.entries()] == [1, 2]
    assert sharded.find_latest()[0] == 2
    b = PartitionedTally(mesh, N, TallyConfig(**CFG), n_parts=8)
    assert sharded.restore_latest(b) == 2


def test_uncommitted_shard_dir_swept_on_construction(mesh, tmp_path):
    d = tmp_path / "cks"
    d.mkdir()
    orphan = d / "ckpt-00000005.shards"
    orphan.mkdir()
    (orphan / "shard-000.npz").write_bytes(b"half-written")
    (orphan / "shard-001.npz.tmp-abc").write_bytes(b"tmp litter")
    CheckpointStore(str(d))
    assert not orphan.exists()


# ===================================================================== #
# Chip loss: coordinated rollback + elastic mesh-shrink (acceptance)
# ===================================================================== #
def test_chip_down_elastic_recovery(mesh, tmp_path):
    """ISSUE 12 acceptance: chip_down_at_move on the 8-device CPU mesh
    → automatic rollback + re-partition onto the 7 survivors, and the
    completed run's flux matches a fault-free run at the shrunk part
    count (the layout-independence oracle)."""
    ref = _reference(mesh, 7, 5)

    t = PartitionedTally(mesh, N, TallyConfig(**CFG), n_parts=8)
    run = ResilientRunner(
        t, str(tmp_path / "cks"), every_moves=2,
        handle_signals=False, sleep=lambda s: None,
        faults=FaultInjector(parse_faults("chip_down_at_move:3")),
    )
    run.initialize_particle_location(_pos())
    for i in range(1, 6):
        run.move_to_next_location(*_inputs(i))

    assert run.tally.n_parts == 7
    assert run.tally is not t  # rebuilt facade
    assert run.recovery_stats["reshards"] == 1
    assert run.recovery_stats["lost_moves"] == 0  # snapshot rollback
    np.testing.assert_allclose(
        np.asarray(run.raw_flux), np.asarray(ref.raw_flux),
        rtol=0, atol=1e-11,
    )
    np.testing.assert_array_equal(run.tally.elem_global, ref.elem_global)
    # Telemetry continuity across the reshard: the transplanted
    # registry carries the counters (served by the same exporter).
    m = t.metrics
    assert m.counter("pumi_elastic_reshards_total").value() == 1
    assert m.counter("pumi_rollbacks_total").value(
        cause="chip-lost"
    ) == 1
    assert run.tally.metrics is m
    # The dead chip reports unhealthy, all survivors healthy.
    assert m.gauge("pumi_chip_health").value(chip="7") == 0.0
    assert m.gauge("pumi_chip_health").value(chip="0") == 1.0
    # The post-reshard generation is sharded at the NEW part count.
    assert run.store.find_latest() is not None
    run.checkpoint()
    assert run.store.last_shards == 7
    run.close()


def test_chip_down_names_the_chip(mesh, tmp_path):
    """``chip:C`` kills a specific chip; the survivors keep mesh
    order."""
    t = PartitionedTally(mesh, N, TallyConfig(**CFG), n_parts=8)
    devs_before = list(t.device_mesh.devices.flat)
    run = ResilientRunner(
        t, str(tmp_path / "cks"), every_moves=100,
        handle_signals=False, sleep=lambda s: None,
        faults=FaultInjector(parse_faults("chip_down_at_move:2,chip:3")),
    )
    run.initialize_particle_location(_pos())
    for i in range(1, 3):
        run.move_to_next_location(*_inputs(i))
    survivors = list(run.tally.device_mesh.devices.flat)
    assert survivors == devs_before[:3] + devs_before[4:]
    # Downed chips are pinned by DEVICE identity, not index: on the
    # re-indexed 7-part mesh every survivor must probe healthy (an
    # index-based set would alias onto a living chip and trigger
    # spurious cascading reshards).
    health = run.coordinator.probe_chips()
    assert all(health.values()) and len(health) == 7
    assert devs_before[3] in run.coordinator.downed_devices
    run.close()


def test_chip_down_megastep_path(mesh, tmp_path):
    """The device-sourced fused loop recovers through the same
    coordinated path: slot state is dropped and re-distributed on the
    shrunken layout, and the automatic recovery is BITWISE equal to a
    deliberate migration at the same boundary (run K moves on 8
    parts, checkpoint, restore on 7, continue). That is the honest
    megastep oracle: the fused loop's device-resident trajectory is
    layout-sensitive in boundary tie-breaks even fault-free (the
    per-move facade's whole-run cross-layout oracle is pinned by
    test_chip_down_elastic_recovery above)."""
    from pumiumtally_tpu.ops.source import SourceParams

    src = SourceParams(default_sigma_t=4.0, seed=11)
    cfg = TallyConfig(**CFG, megastep=2)

    # Deliberate migration reference: 2 moves on 8 parts, sharded
    # checkpoint, restore under 7 parts, 4 more moves.
    a = PartitionedTally(mesh, N, cfg, n_parts=8)
    a.initialize_particle_location(_pos())
    a.run_source_moves(2, src, weights=np.ones(N))
    a.save_checkpoint(str(tmp_path / "mig.shards"))
    ref = PartitionedTally(mesh, N, cfg, n_parts=7)
    ref.restore_checkpoint(str(tmp_path / "mig.shards"))
    ref.run_source_moves(4, src)

    # Automatic recovery: chip 7 dies at move 3 (the second chunk).
    t = PartitionedTally(mesh, N, cfg, n_parts=8)
    with ResilientRunner(
        t, str(tmp_path / "faulty"), every_moves=2,
        handle_signals=False, sleep=lambda s: None,
        faults=FaultInjector(parse_faults("chip_down_at_move:3")),
    ) as run:
        run.initialize_particle_location(_pos())
        run.run_source_moves(6, src, weights=np.ones(N))
        got, stats = run.tally, run.recovery_stats

    assert got.n_parts == 7 and stats["reshards"] == 1
    np.testing.assert_allclose(
        np.asarray(got.raw_flux), np.asarray(ref.raw_flux),
        rtol=0, atol=0,
    )


def test_same_layout_rollback_stays_bitwise(mesh, tmp_path):
    """The transient rung of the taxonomy on the partitioned facade:
    same-layout coordinated rollback replays BITWISE."""
    ref = _reference(mesh, 8, 3)
    t = PartitionedTally(mesh, N, TallyConfig(**CFG), n_parts=8)
    run = ResilientRunner(
        t, str(tmp_path / "cks"), every_moves=100,
        handle_signals=False, sleep=lambda s: None,
        faults=FaultInjector(FaultPlan(transient_at_move=2)),
    )
    run.initialize_particle_location(_pos())
    for i in range(1, 4):
        run.move_to_next_location(*_inputs(i))
    assert t.n_parts == 8 and run.tally is t  # no reshard
    assert run.recovery_stats["rollbacks"] == 1
    assert run.recovery_stats["reshards"] == 0
    np.testing.assert_allclose(
        np.asarray(t.raw_flux), np.asarray(ref.raw_flux),
        rtol=0, atol=0,
    )
    assert t.metrics.counter("pumi_rollbacks_total").value(
        cause="transient"
    ) == 1


def test_chip_loss_without_elastic_flushes_and_raises(mesh, tmp_path):
    """elastic=False (or a facade with nothing to shrink onto) is
    declared graceful degradation: flush the last-good generation,
    then propagate."""
    t = PartitionedTally(mesh, N, TallyConfig(**CFG), n_parts=8)
    run = ResilientRunner(
        t, str(tmp_path / "cks"), every_moves=100,
        handle_signals=False, sleep=lambda s: None, elastic=False,
        faults=FaultInjector(parse_faults("chip_down_at_move:2")),
    )
    run.initialize_particle_location(_pos())
    run.move_to_next_location(*_inputs(1))
    with pytest.raises(ChipLostError):
        run.move_to_next_location(*_inputs(2))
    # The flush wrote the last GOOD iteration (1), not in-flight state.
    assert run.store.find_latest()[0] == 1
    assert t.metrics.counter("pumi_rollbacks_total").value(
        cause="chip-lost"
    ) == 1


def test_chip_loss_plain_facade_degrades_gracefully(tmp_path):
    """The single-chip facade has no smaller mesh: chip-lost flushes
    last-good and propagates."""
    from pumiumtally_tpu import PumiTally, build_box

    mesh32 = build_box(1.0, 1.0, 1.0, 3, 3, 3)
    t = PumiTally(mesh32, N, TallyConfig(tolerance=1e-6))
    rng = np.random.default_rng(42)
    run = ResilientRunner(
        t, str(tmp_path / "cks"), every_moves=100,
        handle_signals=False, sleep=lambda s: None,
        faults=FaultInjector(parse_faults("chip_down_at_move:1")),
    )
    run.initialize_particle_location(
        rng.uniform(0.1, 0.9, (N, 3)).ravel()
    )
    dest = rng.uniform(0.05, 0.95, (N, 3)).ravel()
    with pytest.raises(ChipLostError):
        run.move_to_next_location(
            dest, np.ones(N, np.int8), np.ones(N),
            np.zeros(N, np.int32), np.full(N, -1, np.int32),
        )
    assert run.store.find_latest()[0] == 0


# ===================================================================== #
# Preemption mid-move / mid-retry: the flush writes LAST-GOOD
# ===================================================================== #
def test_preempt_mid_move_flushes_last_good(mesh, tmp_path):
    """``preempt_at_move`` lands INSIDE the supervised dispatch: the
    flushed generation is the last-good one, never in-flight state,
    and the notice propagates like a real eviction."""
    t = PartitionedTally(mesh, N, TallyConfig(**CFG), n_parts=8)
    run = ResilientRunner(
        t, str(tmp_path / "cks"), every_moves=100,
        handle_signals=False, sleep=lambda s: None,
        faults=FaultInjector(parse_faults("preempt_at_move:3")),
    )
    run.initialize_particle_location(_pos())
    for i in (1, 2):
        run.move_to_next_location(*_inputs(i))
    with pytest.raises(InjectedPreemption):
        run.move_to_next_location(*_inputs(3))
    assert run.store.find_latest()[0] == 2
    assert t.iter_count == 2  # rolled back to the boundary
    assert t.metrics.counter("pumi_rollbacks_total").value(
        cause="preempted"
    ) == 1

    # Auto-resume completes the campaign bitwise.
    ref = _reference(mesh, 8, 3)
    b = PartitionedTally(mesh, N, TallyConfig(**CFG), n_parts=8)
    run_b = ResilientRunner(b, str(tmp_path / "cks"),
                            handle_signals=False)
    assert run_b.resumed_from == 2
    run_b.move_to_next_location(*_inputs(3))
    np.testing.assert_allclose(
        b.raw_flux, ref.raw_flux, rtol=0, atol=0
    )


def test_recovery_stats_surface(mesh, tmp_path):
    """The MTTR axes bench.py records: recovery_seconds accumulates
    and lost_moves stays 0 for snapshot rollbacks."""
    t = PartitionedTally(mesh, N, TallyConfig(**CFG), n_parts=8)
    run = ResilientRunner(
        t, str(tmp_path / "cks"), every_moves=100,
        handle_signals=False, sleep=lambda s: None,
        faults=FaultInjector(FaultPlan(transient_at_move=2)),
    )
    run.initialize_particle_location(_pos())
    for i in (1, 2):
        run.move_to_next_location(*_inputs(i))
    st = run.recovery_stats
    assert st["rollbacks"] == 1 and st["reshards"] == 0
    assert st["recovery_seconds"] > 0.0
    assert st["lost_moves"] == 0


# ===================================================================== #
# Chaos scheduling
# ===================================================================== #
def test_chaos_plan_is_seeded_and_deterministic():
    a = chaos_plan("transients:3,chip_down:1,preempt:1,seed:7", 12)
    b = chaos_plan("transients:3,chip_down:1,preempt:1,seed:7", 12)
    assert a == b
    assert len(a.transient_moves) == 3
    assert all(2 <= m <= 11 for m in a.transient_moves)
    assert a.chip_down_move is not None
    assert a.preempt_move >= max(
        [*a.transient_moves, a.chip_down_move]
    )
    c = chaos_plan("transients:3,chip_down:1,preempt:1,seed:8", 12)
    assert c != a
    with pytest.raises(ValueError, match="unknown chaos clause"):
        chaos_plan("explode:1", 12)


def test_chaos_fault_during_recovery_composition(mesh, tmp_path):
    """A transient striking the SAME move as the chip loss: the replay
    after the reshard absorbs it (fault-during-recovery), and the run
    still completes physics-equal to the shrunk-layout reference."""
    from pumiumtally_tpu.resilience.faultinject import ChaosPlan

    ref = _reference(mesh, 7, 5)
    plan = ChaosPlan(transient_moves=(3,), chip_down_move=3)
    t = PartitionedTally(mesh, N, TallyConfig(**CFG), n_parts=8)
    run = ResilientRunner(
        t, str(tmp_path / "cks"), every_moves=2,
        handle_signals=False, sleep=lambda s: None,
        faults=ChaosInjector(plan),
    )
    run.initialize_particle_location(_pos())
    for i in range(1, 6):
        run.move_to_next_location(*_inputs(i))
    assert run.tally.n_parts == 7
    assert run.recovery_stats["rollbacks"] >= 2  # transient + reshard
    np.testing.assert_allclose(
        np.asarray(run.raw_flux), np.asarray(ref.raw_flux),
        rtol=0, atol=1e-11,
    )


def test_chaos_torn_generation_plus_preempt_resume(mesh, tmp_path):
    """Corrupt-manifest + eviction composition: the torn generation is
    skipped at resume, the older one restores, and the replayed
    campaign ends bitwise-identical to the uninterrupted reference."""
    from pumiumtally_tpu.resilience.faultinject import ChaosPlan

    ref = _reference(mesh, 8, 4)
    plan = ChaosPlan(preempt_move=4, torn_generation=3)
    d = str(tmp_path / "cks")
    t = PartitionedTally(mesh, N, TallyConfig(**CFG), n_parts=8)
    run = ResilientRunner(
        t, d, every_moves=1, handle_signals=False,
        sleep=lambda s: None, faults=ChaosInjector(plan),
    )
    run.initialize_particle_location(_pos())
    with pytest.raises(InjectedPreemption):
        for i in range(1, 5):
            run.move_to_next_location(*_inputs(i))
    # Writes: init(0), move1, move2(TORN), move3, preempt-flush(3).
    b = PartitionedTally(mesh, N, TallyConfig(**CFG), n_parts=8)
    run_b = ResilientRunner(b, d, every_moves=1, handle_signals=False)
    assert run_b.resumed_from == 3
    for i in range(1, 5):
        if b.iter_count >= i:
            continue
        run_b.move_to_next_location(*_inputs(i))
    np.testing.assert_allclose(
        b.raw_flux, ref.raw_flux, rtol=0, atol=0
    )


# ===================================================================== #
# SIGTERM arriving mid-retry (subprocess; the preemption flush must
# write the last-good generation, never in-flight rolled-back state)
# ===================================================================== #
_MID_RETRY_CHILD = r"""
import os, signal, sys
sys.path.insert(0, sys.argv[2])  # repo root (the package is not installed)
import numpy as np
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

from pumiumtally_tpu import PumiTally, TallyConfig, build_box
from pumiumtally_tpu.resilience import FaultInjector, ResilientRunner

ckdir = sys.argv[1]
mesh = build_box(1.0, 1.0, 1.0, 3, 3, 3)
N = 16
t = PumiTally(mesh, N, TallyConfig(tolerance=1e-6))
rng = np.random.default_rng(42)


def inputs(i):
    r = np.random.default_rng(100 + i)
    return (
        r.uniform(0.05, 0.95, (N, 3)).ravel().copy(),
        np.ones(N, np.int8),
        r.uniform(0.5, 2.0, N),
        r.integers(0, 2, N).astype(np.int32),
        np.full(N, -1, np.int32),
    )


class AlwaysFailFromMove3(FaultInjector):
    def maybe_transient(self, move):
        if move >= 3:
            # Scribble mid-move state BEFORE failing, so a flush of
            # in-flight state would be visible as iter_count >= 90.
            t.iter_count += 90
            from jax.errors import JaxRuntimeError
            raise JaxRuntimeError("device flaking forever")


def sigterm_mid_retry(seconds):
    # The backoff sleep runs MID-RETRY (after rollback, before the
    # replay): a preemption landing here is the satellite's scenario.
    os.kill(os.getpid(), signal.SIGTERM)
    for _ in range(200):
        pass


run = ResilientRunner(
    t, ckdir, every_moves=100, max_retries=2,
    faults=AlwaysFailFromMove3(), sleep=sigterm_mid_retry,
)
run.initialize_particle_location(rng.uniform(0.1, 0.9, (N, 3)).ravel())
for i in range(1, 4):
    run.move_to_next_location(*inputs(i))
"""


@pytest.mark.slow
def test_sigterm_mid_retry_flushes_last_good_subprocess(tmp_path):
    """SIGTERM delivered while the runner is INSIDE the retry path
    (between rollback and replay, with later attempts also failing
    mid-flight): the process must die 128+SIGTERM and the flushed
    generation must be the last GOOD one (iter 2), never the
    scribbled in-flight state."""
    child = tmp_path / "child.py"
    child.write_text(_MID_RETRY_CHILD)
    ckdir = tmp_path / "cks"
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        PUMI_TPU_FAULTS="",
        PUMI_TPU_MEGASTEP="",
        PUMI_TPU_IO_PIPELINE=os.environ.get("PUMI_TPU_IO_PIPELINE", ""),
    )
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.run(
        [sys.executable, str(child), str(ckdir), repo_root],
        capture_output=True, text=True, env=env, timeout=300,
    )
    assert proc.returncode == 128 + 15, proc.stderr
    store = CheckpointStore(str(ckdir))
    it, path = store.find_latest()
    assert it == 2, (it, proc.stderr)
    meta = verify_checkpoint(path)
    assert meta["iter_count"] == 2
