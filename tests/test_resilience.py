"""Resilience subsystem: durable checkpoints (atomic + sha256 +
rotation + corrupt-generation fallback), the ResilientRunner supervisor
(auto-checkpoint, auto-resume, preemption flush, transient retry), the
bad-particle quarantine, and the PUMI_TPU_FAULTS injection harness that
proves each failure mode recovers.

Acceptance contract (ISSUE 2): a run killed mid-move via die_at_move
resumes from the auto-checkpoint and produces BITWISE-identical flux to
an uninterrupted run; a NaN-injected source produces finite flux with
the bad lanes counted in telemetry()["quarantined"], not a crash.
"""
from __future__ import annotations

import json
import os
import signal

import numpy as np
import pytest

from pumiumtally_tpu import (
    CheckpointStore,
    PumiTally,
    ResilientRunner,
    TallyConfig,
    build_box,
)
from pumiumtally_tpu.resilience import (
    FaultInjector,
    InjectedKill,
    InjectedTransientFault,
    parse_faults,
)
from pumiumtally_tpu.utils.checkpoint import (
    verify_checkpoint,
)

N = 16


@pytest.fixture(scope="module")
def mesh():
    return build_box(1.0, 1.0, 1.0, 4, 4, 4)


def _fresh(mesh, **cfg_kw):
    t = PumiTally(
        mesh, N, TallyConfig(tolerance=1e-6, **cfg_kw)
    )
    rng = np.random.default_rng(42)
    t.initialize_particle_location(
        rng.uniform(0.1, 0.9, (N, 3)).ravel()
    )
    return t


def _inputs(i):
    """Deterministic per-move inputs, so an interrupted run can REPLAY
    the exact moves an uninterrupted run made."""
    rng = np.random.default_rng(100 + i)
    return (
        rng.uniform(0.05, 0.95, (N, 3)).ravel().copy(),
        np.ones(N, np.int8),
        rng.uniform(0.5, 2.0, N),
        rng.integers(0, 2, N).astype(np.int32),
        np.full(N, -1, np.int32),
    )


def _drive(t, first, last):
    for i in range(first, last + 1):
        t.move_to_next_location(*_inputs(i))


# ===================================================================== #
# Durable checkpoints
# ===================================================================== #
def test_atomic_save_never_leaves_truncated_file(
    mesh, tmp_path, monkeypatch
):
    """A crash/ENOSPC mid-write must leave the previous generation
    intact under the real name — and no temp litter."""
    ckpt = str(tmp_path / "t.npz")
    t = _fresh(mesh)
    _drive(t, 1, 1)
    t.save_checkpoint(ckpt)
    before = open(ckpt, "rb").read()

    def boom(f, **arrays):
        f.write(b"PK\x03\x04 partial garbage")
        raise OSError(28, "No space left on device")

    monkeypatch.setattr(np, "savez_compressed", boom)
    _drive(t, 2, 2)
    with pytest.raises(OSError):
        t.save_checkpoint(ckpt)
    monkeypatch.undo()
    assert open(ckpt, "rb").read() == before  # old generation intact
    assert verify_checkpoint(ckpt)["iter_count"] == 1
    assert not [p for p in os.listdir(tmp_path) if ".tmp-" in p]


def test_digest_detects_corruption(mesh, tmp_path):
    ckpt = str(tmp_path / "t.npz")
    t = _fresh(mesh)
    _drive(t, 1, 1)
    t.save_checkpoint(ckpt)
    meta = verify_checkpoint(ckpt)
    assert set(meta["array_sha256"]) >= {"flux", "origin", "elem"}

    FaultInjector(parse_faults("corrupt_ckpt")).corrupt_file(ckpt)
    with pytest.raises(Exception):
        verify_checkpoint(ckpt)
    b = _fresh(mesh)
    with pytest.raises(Exception):
        b.restore_checkpoint(ckpt)
    # Failed restore must not have half-applied anything.
    assert b.iter_count == 0


def _tamper_meta(path, **fields):
    with np.load(path) as z:
        arrays = {k: z[k] for k in z.files}
    meta = json.loads(bytes(arrays.pop("meta").tobytes()).decode())
    meta.update(fields)
    np.savez_compressed(
        path,
        meta=np.frombuffer(json.dumps(meta).encode(), np.uint8),
        **arrays,
    )


def test_dtype_mismatch_rejected(mesh, tmp_path):
    """An f64 checkpoint restored into an f32 tally would silently cast
    the accumulator; the validator must raise instead (like sd_mode)."""
    ckpt = str(tmp_path / "t.npz")
    t = _fresh(mesh)
    t.save_checkpoint(ckpt)
    _tamper_meta(ckpt, dtype="float64")
    b = _fresh(mesh)
    with pytest.raises(ValueError, match="dtype"):
        b.restore_checkpoint(ckpt)


def test_store_rotation_and_corrupt_fallback(mesh, tmp_path):
    store = CheckpointStore(str(tmp_path / "cks"), keep=2)
    t = _fresh(mesh)
    for i in range(1, 4):
        _drive(t, i, i)
        store.save(t)
    its = [it for it, _ in store.entries()]
    assert its == [2, 3]  # keep-2 rotation dropped generation 1
    latest = store.find_latest()
    assert latest is not None and latest[0] == 3

    # Corrupt the newest generation: find_latest and restore_latest
    # must fall back to the previous one.
    FaultInjector(parse_faults("corrupt_ckpt")).corrupt_file(
        store.path_for(3)
    )
    assert store.find_latest()[0] == 2
    b = _fresh(mesh)
    assert store.restore_latest(b) == 2
    assert b.iter_count == 2

    # Everything corrupt: nothing to resume.
    FaultInjector(parse_faults("corrupt_ckpt")).corrupt_file(
        store.path_for(2)
    )
    assert store.find_latest() is None
    assert store.restore_latest(_fresh(mesh)) is None


def test_mismatched_checkpoint_still_raises(mesh, tmp_path):
    """Corruption falls back; a clean-but-incompatible generation is a
    caller bug and must propagate, not be silently skipped."""
    store = CheckpointStore(str(tmp_path / "cks"))
    t = _fresh(mesh)
    store.save(t)
    wrong = PumiTally(
        build_box(1.0, 1.0, 1.0, 2, 2, 2), N,
        TallyConfig(tolerance=1e-6),
    )
    with pytest.raises(ValueError, match="different mesh"):
        store.restore_latest(wrong)


# ===================================================================== #
# Fault grammar
# ===================================================================== #
def test_parse_faults_grammar():
    p = parse_faults("nan_src:0.01,die_at_move:3,corrupt_ckpt,seed:5")
    assert (p.nan_src, p.die_at_move, p.corrupt_ckpt, p.seed) == (
        0.01, 3, True, 5,
    )
    assert not parse_faults("").any()
    assert parse_faults("transient_at_move:2").transient_at_move == 2
    # Elastic fault-tolerance modes (ISSUE 12).
    p = parse_faults("chip_down_at_move:4,chip:2,preempt_at_move:6")
    assert (p.chip_down_at_move, p.chip, p.preempt_at_move) == (4, 2, 6)
    assert p.any()
    assert parse_faults("torn_shard:2").torn_shard == 2
    with pytest.raises(ValueError, match="unknown fault"):
        parse_faults("explode:1")
    with pytest.raises(ValueError, match="probability"):
        parse_faults("nan_src:2.0")
    with pytest.raises(ValueError, match="torn_shard"):
        parse_faults("torn_shard:0")


def test_plan_from_env(monkeypatch):
    monkeypatch.setenv("PUMI_TPU_FAULTS", "nan_src:0.5,seed:9")
    inj = FaultInjector()
    assert inj.plan.nan_src == 0.5 and inj.plan.seed == 9
    d = np.zeros((N, 3))
    hit = inj.corrupt_destinations(d, move=1)
    assert hit > 0 and np.isnan(d).any()
    # Deterministic per (seed, move): a replay injects the same lanes.
    d2 = np.zeros((N, 3))
    assert FaultInjector().corrupt_destinations(d2, move=1) == hit
    np.testing.assert_array_equal(np.isnan(d), np.isnan(d2))


# ===================================================================== #
# The supervisor
# ===================================================================== #
def test_die_at_move_resume_bitwise_identical(mesh, tmp_path):
    """ISSUE 2 acceptance: kill at move 4, auto-resume, replay —
    bitwise-identical flux to an uninterrupted run with the same
    inputs."""
    ref = _fresh(mesh)
    _drive(ref, 1, 5)

    d = str(tmp_path / "cks")
    a = PumiTally(mesh, N, TallyConfig(tolerance=1e-6))
    run_a = ResilientRunner(
        a, d, every_moves=1, handle_signals=False,
        faults=FaultInjector(parse_faults("die_at_move:4")),
    )
    rng = np.random.default_rng(42)
    run_a.initialize_particle_location(
        rng.uniform(0.1, 0.9, (N, 3)).ravel()
    )
    with pytest.raises(InjectedKill):
        for i in range(1, 6):
            run_a.move_to_next_location(*_inputs(i))
    assert a.iter_count == 3  # died before move 4 ran

    b = PumiTally(mesh, N, TallyConfig(tolerance=1e-6))
    run_b = ResilientRunner(b, d, every_moves=1, handle_signals=False)
    assert run_b.resumed_from == 3
    # The resume-aware driver loop: initialize is a no-op, replayed
    # moves are skipped by iter_count.
    run_b.initialize_particle_location(
        rng.uniform(0.1, 0.9, (N, 3)).ravel()
    )
    for i in range(1, 6):
        if b.iter_count >= i:
            continue
        run_b.move_to_next_location(*_inputs(i))
    run_b.close()

    np.testing.assert_array_equal(
        np.asarray(b.raw_flux), np.asarray(ref.raw_flux)
    )
    np.testing.assert_array_equal(b.element_ids, ref.element_ids)
    assert b.total_segments == ref.total_segments
    assert b.metrics.counter("pumi_resumes_total").value() == 1


def test_transient_retry_with_backoff(mesh, tmp_path):
    """A transient failure mid-run rolls back to the last good state
    and retries; the completed run matches an undisturbed one."""
    ref = _fresh(mesh)
    _drive(ref, 1, 3)

    delays = []
    t = _fresh(mesh)
    run = ResilientRunner(
        t, str(tmp_path / "cks"), every_moves=10,
        handle_signals=False, max_retries=3, backoff_base=0.25,
        faults=FaultInjector(parse_faults("transient_at_move:2")),
        sleep=delays.append,
    )
    _drive(run, 1, 3)
    np.testing.assert_array_equal(
        np.asarray(t.raw_flux), np.asarray(ref.raw_flux)
    )
    assert delays == [0.25]  # one retry, exponential base
    assert t.metrics.counter("pumi_move_retries_total").value() == 1


def test_retry_snapshots_off_propagates_transients(mesh, tmp_path):
    """retry_snapshots=False trades the per-move flux readback for no
    in-process retry: transients propagate, auto-resume is the
    recovery path."""
    t = _fresh(mesh)
    run = ResilientRunner(
        t, str(tmp_path / "cks"), handle_signals=False,
        retry_snapshots=False, sleep=lambda s: None,
        faults=FaultInjector(parse_faults("transient_at_move:1")),
    )
    assert run._good is None
    with pytest.raises(InjectedTransientFault):
        run.move_to_next_location(*_inputs(1))


def test_store_sweeps_orphaned_tmp_files(mesh, tmp_path):
    """A SIGKILL mid-write leaves atomic_savez's temp file behind; the
    store sweeps it on construction instead of hoarding it forever."""
    d = tmp_path / "cks"
    d.mkdir()
    orphan = d / "ckpt-00000001.npz.tmp-abc123"
    orphan.write_bytes(b"half-written garbage")
    store = CheckpointStore(str(d))
    assert not orphan.exists()
    t = _fresh(mesh)
    store.save(t)
    assert store.find_latest() is not None


def test_transient_exhausts_retries(mesh, tmp_path):
    class AlwaysTransient(FaultInjector):
        def maybe_transient(self, move):
            raise InjectedTransientFault("flaky forever")

    t = _fresh(mesh)
    run = ResilientRunner(
        t, str(tmp_path / "cks"), handle_signals=False,
        max_retries=2, faults=AlwaysTransient(), sleep=lambda s: None,
    )
    with pytest.raises(InjectedTransientFault):
        run.move_to_next_location(*_inputs(1))


def test_sigterm_flushes_final_checkpoint(mesh, tmp_path):
    """Preemption contract: SIGTERM writes one final generation before
    the process dies (SystemExit under the default prior handler)."""
    t = _fresh(mesh)
    store = CheckpointStore(str(tmp_path / "cks"))
    run = ResilientRunner(t, store, every_moves=1000)
    try:
        _drive(run, 1, 2)
        assert store.find_latest() is None  # nothing written yet
        with pytest.raises(SystemExit) as exc:
            os.kill(os.getpid(), signal.SIGTERM)
            # Signal delivery happens at the next bytecode boundary.
            for _ in range(100):
                pass
        assert exc.value.code == 128 + signal.SIGTERM
        assert store.find_latest()[0] == 2  # flushed at current iter
        # Handler restored: a second SIGTERM would be the default
        # action; make sure ours is gone before leaving the test.
        assert signal.getsignal(signal.SIGTERM) == signal.SIG_DFL
    finally:
        run._uninstall_signal_handlers()


def test_pending_signal_delivered_when_move_raises(mesh, tmp_path):
    """A preemption signal deferred mid-move must still flush and kill
    the process when the move RAISES — swallowing it would leave a
    process that ignores SIGTERM forever."""
    t = _fresh(mesh)
    store = CheckpointStore(str(tmp_path / "cks"))
    run = ResilientRunner(t, store, every_moves=1000)
    try:
        def bad_move(*args, **kwargs):
            os.kill(os.getpid(), signal.SIGTERM)
            for _ in range(100):  # let the handler run (deferred)
                pass
            raise RuntimeError("driver bug mid-move")

        t.move_to_next_location = bad_move
        with pytest.raises(SystemExit) as exc:
            run.move_to_next_location(*_inputs(1))
        assert exc.value.code == 128 + signal.SIGTERM
        # The flush wrote the last consistent state (post-init).
        assert store.find_latest()[0] == 0
    finally:
        run._uninstall_signal_handlers()


def test_corrupt_ckpt_fault_through_runner(mesh, tmp_path):
    """The corrupt_ckpt fault corrupts every generation the supervisor
    writes; resume must then find nothing valid."""
    t = _fresh(mesh)
    run = ResilientRunner(
        t, str(tmp_path / "cks"), every_moves=1,
        handle_signals=False,
        faults=FaultInjector(parse_faults("corrupt_ckpt")),
    )
    _drive(run, 1, 2)
    assert len(run.store.entries()) >= 1
    assert run.store.find_latest() is None


def test_nan_source_quarantined_not_crash(mesh, tmp_path):
    """ISSUE 2 acceptance: a NaN-injected source produces finite flux
    with the bad lanes counted in telemetry()["quarantined"]."""
    t = PumiTally(
        mesh, N, TallyConfig(tolerance=1e-6, quarantine=True)
    )
    rng = np.random.default_rng(42)
    run = ResilientRunner(
        t, str(tmp_path / "cks"), every_moves=1000,
        handle_signals=False,
        faults=FaultInjector(parse_faults("nan_src:0.3,seed:7")),
    )
    run.initialize_particle_location(
        rng.uniform(0.1, 0.9, (N, 3)).ravel()
    )
    _drive(run, 1, 3)
    tm = t.telemetry()
    assert tm["quarantined"] > 0
    assert tm["quarantined"] == tm["totals"]["quarantined"]
    assert np.isfinite(np.asarray(t.raw_flux)).all()
    assert t.quarantined_lanes().sum() == tm["quarantined"]
    inj = t.metrics.counter("pumi_injected_faults_total")
    assert inj.value(kind="nan_src") == tm["quarantined"]


# ===================================================================== #
# Quarantine semantics (facade-level, no injector)
# ===================================================================== #
def test_quarantine_masks_and_reports_per_lane(mesh):
    t = PumiTally(
        mesh, N, TallyConfig(tolerance=1e-6, quarantine=True)
    )
    rng = np.random.default_rng(42)
    pos = rng.uniform(0.1, 0.9, (N, 3))
    t.initialize_particle_location(pos.ravel())

    dest, fly, w, g, mats = _inputs(1)
    d3 = dest.reshape(N, 3)
    d3[3] = np.nan          # nonfinite_dest
    d3[5] = 1e9             # out_of_mesh
    w = w.copy()
    w[7] = np.inf           # nonfinite_weight
    t.move_to_next_location(dest, fly, w, g, mats)

    lanes = t.quarantined_lanes()
    assert set(np.nonzero(lanes)[0]) == {3, 5, 7}
    # Parked contract: quarantined lanes report their HELD position.
    held = dest.reshape(N, 3)
    clean = PumiTally(mesh, N, TallyConfig(tolerance=1e-6))
    clean.initialize_particle_location(pos.ravel())
    np.testing.assert_allclose(
        held[[3, 5, 7]],
        np.asarray(clean.state.origin)[[3, 5, 7]],
        atol=1e-12,
    )
    assert np.isfinite(np.asarray(t.raw_flux)).all()
    # Per-reason counters, and the deduplicated headline.
    c = t.metrics.counter("pumi_quarantine_reasons_total")
    assert c.value(reason="nonfinite_dest") == 1
    assert c.value(reason="out_of_mesh") == 1
    assert c.value(reason="nonfinite_weight") == 1
    assert t.telemetry()["quarantined"] == 3
    # The caller's weights array is never written through.
    assert np.isinf(w[7])


def test_multi_reason_lane_counts_once(mesh):
    """A lane tripping several reasons in one move is ONE quarantined
    lane: the headline agrees with quarantined_lanes()."""
    t = PumiTally(
        mesh, N, TallyConfig(tolerance=1e-6, quarantine=True)
    )
    rng = np.random.default_rng(42)
    t.initialize_particle_location(
        rng.uniform(0.1, 0.9, (N, 3)).ravel()
    )
    dest, fly, w, g, mats = _inputs(1)
    dest.reshape(N, 3)[3] = 1e9      # out_of_mesh ...
    w = w.copy()
    w[3] = np.nan                    # ... AND nonfinite_weight
    t.move_to_next_location(dest, fly, w, g, mats)
    assert t.telemetry()["quarantined"] == 1
    assert t.quarantined_lanes().sum() == 1
    c = t.metrics.counter("pumi_quarantine_reasons_total")
    assert c.value(reason="out_of_mesh") == 1
    assert c.value(reason="nonfinite_weight") == 1


def test_quarantine_initial_positions(mesh):
    t = PumiTally(
        mesh, N, TallyConfig(tolerance=1e-6, quarantine=True)
    )
    rng = np.random.default_rng(42)
    pos = rng.uniform(0.1, 0.9, (N, 3))
    pos[2] = np.nan
    t.initialize_particle_location(pos.ravel())
    assert t.quarantined_lanes()[2] == 1
    # The masked lane stayed at the element-0 seed (finite state).
    assert np.isfinite(np.asarray(t.state.origin)).all()
    # The caller's array is untouched.
    assert np.isnan(pos[2]).all()


def test_retry_after_walk_failure_keeps_quarantine_semantics(
    mesh, tmp_path
):
    """A transient failure AFTER the quarantine scan (inside the walk)
    must retry against the ORIGINAL inputs: the lane is re-quarantined
    (not walked to the sanitized zeros) and the rolled-back per-lane
    count ends at exactly 1."""
    from jax.errors import JaxRuntimeError

    t = PumiTally(
        mesh, N, TallyConfig(tolerance=1e-6, quarantine=True)
    )
    rng = np.random.default_rng(42)
    t.initialize_particle_location(
        rng.uniform(0.1, 0.9, (N, 3)).ravel()
    )
    orig_trace, fired = t._trace, []

    def flaky(*args, **kwargs):
        if not fired:
            fired.append(True)
            raise JaxRuntimeError("preempted device")
        return orig_trace(*args, **kwargs)

    t._trace = flaky
    run = ResilientRunner(
        t, str(tmp_path / "cks"), every_moves=1000,
        handle_signals=False, sleep=lambda s: None,
    )
    dest, fly, w, g, mats = _inputs(1)
    held = np.asarray(t.state.origin)[4].copy()
    dest.reshape(N, 3)[4] = np.nan
    run.move_to_next_location(dest, fly, w, g, mats)
    assert t.metrics.counter("pumi_move_retries_total").value() == 1
    # Rolled back + re-counted once, not twice.
    assert t.quarantined_lanes()[4] == 1
    # The retried lane was parked at its HELD position, not walked to
    # the sanitized (0,0,0).
    np.testing.assert_allclose(
        dest.reshape(N, 3)[4], held, atol=1e-12
    )
    assert np.isfinite(np.asarray(t.raw_flux)).all()


def test_retry_after_copyback_failure_rearms_out_params(
    mesh, tmp_path
):
    """A retryable error surfacing AFTER the facade's copy-back (e.g.
    the late xpoints fetch) has already zeroed the caller's flying
    flags and overwritten dest — the retry must re-arm the original
    inputs, not walk zero particles and silently drop the move."""
    from jax.errors import JaxRuntimeError

    cfg = TallyConfig(tolerance=1e-6, record_xpoints=4)
    ref = PumiTally(mesh, N, cfg)
    t = PumiTally(mesh, N, cfg)
    pos = np.random.default_rng(42).uniform(0.1, 0.9, (N, 3))
    for x in (ref, t):
        x.initialize_particle_location(pos.ravel().copy())
    ref.move_to_next_location(*_inputs(1))

    orig, fired = t._store_xpoints, []

    def flaky(result):
        if not fired:
            fired.append(True)
            raise JaxRuntimeError("device lost at xpoints fetch")
        return orig(result)

    t._store_xpoints = flaky
    run = ResilientRunner(
        t, str(tmp_path / "cks"), every_moves=1000,
        handle_signals=False, sleep=lambda s: None,
    )
    run.move_to_next_location(*_inputs(1))
    assert t.iter_count == 1
    np.testing.assert_array_equal(
        np.asarray(t.raw_flux), np.asarray(ref.raw_flux)
    )
    xp_t, c_t = t.intersection_points()
    xp_r, c_r = ref.intersection_points()
    np.testing.assert_array_equal(c_t, c_r)


def test_quarantined_lanes_ride_checkpoints(mesh, tmp_path):
    """Per-lane quarantine counts are resumable state: a resumed run
    keeps its degraded-mode report."""
    ckpt = str(tmp_path / "t.npz")
    t = PumiTally(
        mesh, N, TallyConfig(tolerance=1e-6, quarantine=True)
    )
    rng = np.random.default_rng(42)
    t.initialize_particle_location(
        rng.uniform(0.1, 0.9, (N, 3)).ravel()
    )
    dest, fly, w, g, mats = _inputs(1)
    dest.reshape(N, 3)[6] = np.nan
    t.move_to_next_location(dest, fly, w, g, mats)
    t.save_checkpoint(ckpt)

    b = PumiTally(
        mesh, N, TallyConfig(tolerance=1e-6, quarantine=True)
    )
    b.restore_checkpoint(ckpt)
    np.testing.assert_array_equal(
        b.quarantined_lanes(), t.quarantined_lanes()
    )


def test_quarantine_off_keeps_loud_failure(mesh):
    t = PumiTally(
        mesh, N,
        TallyConfig(tolerance=1e-6, checkify_invariants=True),
    )
    rng = np.random.default_rng(42)
    t.initialize_particle_location(
        rng.uniform(0.1, 0.9, (N, 3)).ravel()
    )
    dest, fly, w, g, mats = _inputs(1)
    dest.reshape(N, 3)[0] = np.nan
    with pytest.raises(ValueError, match="non-finite"):
        t.move_to_next_location(dest, fly, w, g, mats)
