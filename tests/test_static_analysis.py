"""Static-analysis subsystem tests (pumiumtally_tpu/analysis/).

Layer 1 (astlint): positive AND negative fixture snippets per rule —
every rule must fire on its target pattern and stay quiet on the
sanctioned idiom next to it.  Layer 2 (contracts): the extraction and
invariant machinery is exercised against real traced programs, then
regressions are INJECTED — an extra in-program transfer in a wrapped
step, a host callback, a dropped donation, an f64 leak, a scan degraded
away — and the named invariant must fire.  Finally the whole runner
(scripts/lint.py) must exit 0 on the repo itself: the codebase stays
lint-clean, and CONTRACTS.json matches the committed programs.
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
from pathlib import Path

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from pumiumtally_tpu.analysis import (
    Finding,
    apply_baseline,
    load_baseline,
)
from pumiumtally_tpu.analysis.astlint import lint_package, lint_sources

ROOT = Path(__file__).resolve().parents[1]


def rules_of(findings):
    return sorted({f.rule for f in findings})


def at(findings, rule):
    return [f for f in findings if f.rule == rule]


# --------------------------------------------------------------------- #
# PUMI001: host sync in traced bodies
# --------------------------------------------------------------------- #
def test_host_sync_fires_in_jitted_fn():
    src = """
import jax, jax.numpy as jnp
import numpy as np

def step(x, y):
    s = jnp.sum(x)
    bad = float(s)
    return bad * y

_jit = jax.jit(step)
"""
    fs = lint_sources({"pumiumtally_tpu/ops/fake.py": src})
    assert [f.rule for f in fs] == ["PUMI001"]
    assert fs[0].symbol == "step"
    assert "float()" in fs[0].message


def test_host_sync_item_and_asarray_fire_via_call_graph():
    # helper() is not itself jitted, but the traced step calls it —
    # the package-wide fixpoint must propagate tracedness into it.
    src = """
import jax, jax.numpy as jnp
import numpy as np

def helper(v):
    return np.asarray(v)

def step(x):
    n = x.item()
    return helper(x) + n

_jit = jax.jit(step)
"""
    fs = lint_sources({"pumiumtally_tpu/ops/fake.py": src})
    assert len(at(fs, "PUMI001")) == 2
    assert {f.symbol for f in fs} == {"step", "helper"}


def test_host_sync_quiet_on_host_fn_and_static_knobs():
    src = """
import jax, jax.numpy as jnp
import numpy as np

def host_reader(x):
    return float(np.asarray(x).sum())  # never traced: fine

def step(x, *, stages):
    # kw-only params are the static-knob convention: probing them at
    # trace time is sanctioned.
    k = int(stages)
    n = x.shape[0]        # static metadata of a traced array
    m = int(n)            # derived static: fine
    return x * k * m

_jit = jax.jit(step, static_argnames=("stages",))
"""
    fs = lint_sources({"pumiumtally_tpu/ops/fake.py": src})
    assert fs == []


def test_device_get_always_fires_in_traced():
    src = """
import jax

def body(c, t):
    jax.device_get(c)
    return c, t

def run(xs):
    import jax.numpy as jnp
    from jax import lax
    return lax.scan(body, jnp.zeros(3), xs)
"""
    fs = lint_sources({"pumiumtally_tpu/ops/fake.py": src})
    # PUMI001 (host sync in traced body) AND PUMI002 (transfer outside
    # the staging modules) both apply — the call breaks two contracts.
    assert rules_of(fs) == ["PUMI001", "PUMI002"]
    assert at(fs, "PUMI001")[0].symbol == "body"


# --------------------------------------------------------------------- #
# PUMI002: transfers outside the staging modules
# --------------------------------------------------------------------- #
def test_transfer_outside_staging_fires():
    src = """
import jax

def leak(x):
    return jax.device_put(x)
"""
    fs = lint_sources({"pumiumtally_tpu/obs/fake.py": src})
    assert [f.rule for f in fs] == ["PUMI002"]


def test_transfer_in_approved_module_clean():
    src = """
import jax

def stage(x):
    return jax.device_put(x)
"""
    fs = lint_sources({"pumiumtally_tpu/api.py": src})
    assert fs == []


# --------------------------------------------------------------------- #
# PUMI003: use after donate
# --------------------------------------------------------------------- #
_DONATE_MODULE = """
import jax, jax.numpy as jnp

def impl(state, flux):
    return state + 1, flux + state

_step = jax.jit(impl, donate_argnames=("flux",))

def step(*args, **kwargs):
    return _step(*args, **kwargs)
"""


def test_use_after_donate_fires_on_kwarg_and_positional():
    src = _DONATE_MODULE + """
def caller(state, flux):
    out = _step(state, flux=flux)
    return flux.sum() + out[0]

def caller_pos(state, flux):
    out = _step(state, flux)
    return flux.sum() + out[0]
"""
    fs = lint_sources({"pumiumtally_tpu/ops/fake.py": src})
    assert len(at(fs, "PUMI003")) == 2
    assert {f.symbol for f in at(fs, "PUMI003")} == {
        "caller", "caller_pos"
    }


def test_use_after_donate_quiet_after_rebind_and_via_wrapper():
    src = _DONATE_MODULE + """
def good(state, flux):
    state2, flux = _step(state, flux=flux)
    return flux.sum() + state2

def wrapper_caller(state, flux):
    out = step(state, flux=flux)   # pass-through wrapper donates too
    return flux.sum()
"""
    fs = lint_sources({"pumiumtally_tpu/ops/fake.py": src})
    assert {f.symbol for f in at(fs, "PUMI003")} == {"wrapper_caller"}


def test_use_after_donate_tracks_self_attributes():
    src = _DONATE_MODULE + """
class Facade:
    def move(self):
        out = _step(self.state, flux=self.flux)
        self.state = out[0]
        return self.flux
"""
    fs = lint_sources({"pumiumtally_tpu/ops/fake.py": src})
    assert len(at(fs, "PUMI003")) == 1
    assert "self.flux" in fs[0].message


# --------------------------------------------------------------------- #
# PUMI004: nondeterminism in traced bodies
# --------------------------------------------------------------------- #
def test_nondeterminism_fires_only_in_traced():
    src = """
import time, random
import jax

def step(x):
    t = time.time()
    r = random.random()
    return x + t + r

_jit = jax.jit(step)

def host_bench(x):
    t0 = time.perf_counter()   # host timing: fine
    return t0
"""
    fs = lint_sources({"pumiumtally_tpu/ops/fake.py": src})
    assert [f.rule for f in fs] == ["PUMI004", "PUMI004"]
    assert all(f.symbol == "step" for f in fs)


# --------------------------------------------------------------------- #
# PUMI005: f64 on device paths
# --------------------------------------------------------------------- #
def test_f64_fires_outside_dispatch_and_audit_exempt():
    bad = """
import jax.numpy as jnp

ACC = jnp.zeros(4, jnp.float64)
"""
    fs = lint_sources({"pumiumtally_tpu/ops/fake.py": bad})
    assert [f.rule for f in fs] == ["PUMI005"]
    # integrity/audit.py is the sanctioned f64 surface.
    fs = lint_sources({"pumiumtally_tpu/integrity/audit.py": bad})
    assert fs == []


def test_f64_quiet_in_dtype_dispatch_branch():
    src = """
import jax, jax.numpy as jnp
from jax import lax

def exp2i(k, dtype):
    if dtype == jnp.float64:
        return lax.bitcast_convert_type(
            (k.astype(jnp.int64) + 1023) << 52, jnp.float64
        )
    return jnp.exp2(k)

def unpack(rec):
    dtype = jnp.float32 if rec.dtype == jnp.uint32 else jnp.float64
    return rec.astype(dtype)
"""
    fs = lint_sources({"pumiumtally_tpu/ops/fake.py": src})
    assert fs == []


def test_f64_literal_string_fires_in_traced():
    src = """
import jax, jax.numpy as jnp

def step(x):
    return x.astype("float64")

_jit = jax.jit(step)
"""
    fs = lint_sources({"pumiumtally_tpu/ops/fake.py": src})
    assert [f.rule for f in fs] == ["PUMI005"]


# --------------------------------------------------------------------- #
# PUMI006: jit static hygiene
# --------------------------------------------------------------------- #
def test_jit_inside_loop_fires():
    src = """
import jax

def sweep(xs):
    out = []
    for x in xs:
        out.append(jax.jit(lambda v: v * 2)(x))
    return out
"""
    fs = lint_sources({"pumiumtally_tpu/models/fake.py": src})
    assert [f.rule for f in fs] == ["PUMI006"]


def test_static_loop_var_fires_and_hoisted_clean():
    src = """
import jax

def impl(k, x):
    return x * k

_jit = jax.jit(impl, static_argnums=(0,))

def bad(xs):
    acc = 0
    for i in range(8):
        acc += _jit(i, xs)       # new compile every i
    return acc

def good(xs, k):
    acc = 0
    for i in range(8):
        acc += _jit(k, xs)       # static arg fixed across the loop
    return acc
"""
    fs = lint_sources({"pumiumtally_tpu/models/fake.py": src})
    assert [f.rule for f in fs] == ["PUMI006"]
    assert fs[0].symbol == "bad"


# --------------------------------------------------------------------- #
# PUMI007: guarded-by
# --------------------------------------------------------------------- #
def test_guarded_attr_fires_outside_lock_quiet_inside():
    src = """
import threading

class Rec:
    def __init__(self):
        self._lock = threading.Lock()
        self._seq = 0  # guarded by: self._lock

    def bad(self):
        self._seq += 1

    def good(self):
        with self._lock:
            self._seq += 1
            return self._seq
"""
    fs = lint_sources({"pumiumtally_tpu/obs/fake.py": src})
    assert [f.rule for f in fs] == ["PUMI007"]
    assert fs[0].symbol == "Rec.bad"


def test_event_guard_requires_set_and_wait():
    src = """
import threading

def run(fn, seconds):
    outcome = {}  # guarded by: finished (event)
    finished = threading.Event()

    def target():
        outcome["value"] = fn()   # missing finished.set()

    t = threading.Thread(target=target)
    t.start()
    return outcome.get("value")   # read before finished.wait()
"""
    fs = lint_sources({"pumiumtally_tpu/integrity/fake.py": src})
    msgs = [f.message for f in at(fs, "PUMI007")]
    assert len(msgs) == 2
    assert any("happens-before" in m for m in msgs)
    assert any("may still be writing" in m for m in msgs)


def test_event_guard_clean_pattern():
    src = """
import threading

def run(fn, seconds):
    outcome = {}  # guarded by: finished (event)
    finished = threading.Event()

    def target():
        try:
            outcome["value"] = fn()
        finally:
            finished.set()

    t = threading.Thread(target=target)
    t.start()
    if not finished.wait(seconds):
        raise TimeoutError
    return outcome["value"]
"""
    fs = lint_sources({"pumiumtally_tpu/integrity/fake.py": src})
    assert fs == []


# --------------------------------------------------------------------- #
# scripts/ + bench.py coverage (traced-body rule subset)
# --------------------------------------------------------------------- #
def test_scripts_traced_body_rules_fire():
    """The traced-body contracts travel with the jitted code: a host
    sync / nondeterminism / f64 literal in a script's traced function
    is a finding, exactly as in the package."""
    src = """
import time
import jax, jax.numpy as jnp

def step(x):
    t = time.time()
    s = jnp.sum(x)
    bad = float(s)
    return x.astype("float64") * t * bad

_jit = jax.jit(step)
"""
    fs = lint_sources({"scripts/fake_probe.py": src})
    assert rules_of(fs) == ["PUMI001", "PUMI004", "PUMI005"]
    assert all(f.path == "scripts/fake_probe.py" for f in fs)


def test_scripts_package_scoped_rules_filtered():
    """PUMI002 (transfer placement) and PUMI006 (jit hygiene) are
    package-structure contracts: scripts stage their own transfers and
    microbenches build throwaway jits by design."""
    src = """
import jax

def main(xs):
    staged = jax.device_put(xs)          # scripts stage on purpose
    out = []
    for x in xs:
        out.append(jax.jit(lambda v: v * 2)(x))  # probe-by-config
    return staged, out
"""
    assert lint_sources({"scripts/fake_probe.py": src}) == []
    # ... while the SAME source inside the package keeps both findings.
    fs = lint_sources({"pumiumtally_tpu/obs/fake_probe.py": src})
    assert rules_of(fs) == ["PUMI002", "PUMI006"]


def test_scripts_use_after_donate_fires():
    """bench.py builds donating jits of its own — PUMI003 is in the
    scripts subset because use-after-donate corrupts data no matter
    who constructed the jit."""
    src = """
import jax

def impl(state, flux):
    return state + 1, flux + state

_step = jax.jit(impl, donate_argnames=("flux",))

def measure(state, flux):
    out = _step(state, flux=flux)
    return flux.sum() + out[0]   # read after donation
"""
    fs = lint_sources({"scripts/fake_bench.py": src})
    assert [f.rule for f in fs] == ["PUMI003"]
    assert fs[0].symbol == "measure"


def test_scripts_fixpoint_reaches_into_package():
    """A script jitting a package function makes that function traced:
    the finding lands on the PACKAGE path with the full rule set."""
    pkg = """
def helper(x):
    return float(x)
"""
    script = """
import jax
from pumiumtally_tpu.ops.fake_helper import helper

_jit = jax.jit(helper)
"""
    fs = lint_sources({
        "pumiumtally_tpu/ops/fake_helper.py": pkg,
        "scripts/fake_run.py": script,
    })
    assert [f.rule for f in fs] == ["PUMI001"]
    assert fs[0].path == "pumiumtally_tpu/ops/fake_helper.py"
    assert fs[0].symbol == "helper"


def test_repo_scripts_and_bench_clean_under_subset():
    """The launch surface itself carries no traced-body findings (the
    repo-stays-clean pin for the satellite coverage)."""
    findings = lint_package(ROOT)
    entries = load_baseline(ROOT / "LINT_BASELINE.json")
    kept, _, _ = apply_baseline(findings, entries)
    outside = [f for f in kept
               if not f.path.startswith("pumiumtally_tpu/")]
    assert outside == [], "\n".join(f.render() for f in outside)
    # and the covered files really are in the index
    paths = {f.path for f in findings}
    assert not paths or all(
        p.startswith(("pumiumtally_tpu/", "scripts/", "bench.py"))
        for p in paths
    )


# --------------------------------------------------------------------- #
# Baseline machinery
# --------------------------------------------------------------------- #
def test_baseline_suppresses_by_symbol_and_reports_stale(tmp_path):
    f1 = Finding("PUMI002", "pumiumtally_tpu/obs/x.py", 3, "leak", "m")
    entries = [
        {"rule": "PUMI002", "path": "pumiumtally_tpu/obs/x.py",
         "symbol": "leak", "justification": "test"},
        {"rule": "PUMI001", "path": "pumiumtally_tpu/obs/x.py",
         "symbol": "gone", "justification": "stale"},
    ]
    kept, suppressed, unused = apply_baseline([f1], entries)
    assert kept == [] and len(suppressed) == 1 and len(unused) == 1
    assert unused[0]["symbol"] == "gone"


def test_baseline_rejects_missing_justification(tmp_path):
    p = tmp_path / "b.json"
    p.write_text(json.dumps({"suppressions": [
        {"rule": "PUMI001", "path": "x.py", "symbol": "f",
         "justification": ""}
    ]}))
    with pytest.raises(ValueError, match="justification"):
        load_baseline(p)


def _lint_ast_only(tmp_path, extra_entries, *flags):
    """Run scripts/lint.py --ast-only in a fresh process against the
    committed suppressions plus ``extra_entries``."""
    committed = json.loads(
        (ROOT / "LINT_BASELINE.json").read_text()
    )["suppressions"]
    p = tmp_path / "baseline.json"
    p.write_text(json.dumps(
        {"suppressions": committed + list(extra_entries)}
    ))
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    return subprocess.run(
        [sys.executable, str(ROOT / "scripts" / "lint.py"),
         "--ast-only", "--baseline", str(p), *flags],
        capture_output=True, text=True, env=env, cwd=str(ROOT),
        timeout=300,
    )


def test_stale_baseline_entry_is_a_hard_failure(tmp_path):
    """A suppression whose finding no longer exists must FAIL the run —
    a stale hole is exactly where the next regression slips through."""
    stale = {"rule": "PUMI001", "path": "pumiumtally_tpu/ops/walk.py",
             "symbol": "long_gone_fn",
             "justification": "finding fixed three PRs ago"}
    proc = _lint_ast_only(tmp_path, [stale])
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert "error: stale baseline entry" in proc.stdout
    assert "long_gone_fn" in proc.stdout


def test_allow_stale_escape_hatch_downgrades_to_warning(tmp_path):
    stale = {"rule": "PUMI001", "path": "pumiumtally_tpu/ops/walk.py",
             "symbol": "long_gone_fn",
             "justification": "finding fixed three PRs ago"}
    proc = _lint_ast_only(tmp_path, [stale], "--allow-stale")
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "warning: stale baseline entry" in proc.stdout


def test_clean_baseline_still_exits_zero(tmp_path):
    proc = _lint_ast_only(tmp_path, [])
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_write_flag_for_disabled_layer_is_rejected(tmp_path):
    """`--no-perf --write-perf-contracts` (or an --*-only flag that
    disables the targeted layer) must be a usage error — exiting 0
    without regenerating the baseline would be a silent no-op."""
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    for flags in (["--no-perf", "--write-perf-contracts"],
                  ["--ast-only", "--write-perf-contracts"],
                  ["--perf-only", "--write-contracts"]):
        proc = subprocess.run(
            [sys.executable, str(ROOT / "scripts" / "lint.py"), *flags],
            capture_output=True, text=True, env=env, cwd=str(ROOT),
            timeout=120,
        )
        assert proc.returncode == 2, (flags, proc.stdout, proc.stderr)
        assert "needs the" in proc.stderr, flags


def test_unroutable_baseline_rule_is_a_config_error(tmp_path):
    """A typo'd rule ("UMI001") routes to no lint layer: it would
    suppress nothing AND dodge the stale-entry failure — the runner
    must reject it outright."""
    typo = {"rule": "UMI001", "path": "pumiumtally_tpu/ops/walk.py",
            "symbol": "whatever", "justification": "typo'd rule"}
    proc = _lint_ast_only(tmp_path, [typo])
    assert proc.returncode == 2, proc.stdout + proc.stderr
    assert "matches no lint layer" in proc.stderr


# --------------------------------------------------------------------- #
# The repo itself stays clean
# --------------------------------------------------------------------- #
def test_repo_astlint_clean_modulo_baseline():
    findings = lint_package(ROOT)
    entries = load_baseline(ROOT / "LINT_BASELINE.json")
    kept, _, _ = apply_baseline(findings, entries)
    assert kept == [], "\n".join(f.render() for f in kept)


def test_threaded_surface_is_annotated():
    """The concurrency lint only protects what is annotated: the four
    threaded classes must each declare at least one guarded member."""
    for rel in (
        "pumiumtally_tpu/obs/recorder.py",
        "pumiumtally_tpu/ops/staging.py",
        "pumiumtally_tpu/obs/exporter.py",
        "pumiumtally_tpu/integrity/watchdog.py",
    ):
        text = (ROOT / rel).read_text()
        assert "# guarded by:" in text, f"{rel} lost its annotations"


# --------------------------------------------------------------------- #
# Layer 2: contract extraction + injected regressions
# --------------------------------------------------------------------- #
def _sig_of(jitted, *args, **kwargs):
    from pumiumtally_tpu.analysis.contracts import extract_signature

    return extract_signature(jitted.trace(*args, **kwargs))


def _structural(fam, sig):
    from pumiumtally_tpu.analysis.contracts import check_structural

    return check_structural({"families": {fam: sig}})


def test_extract_signature_shape():
    sig = _sig_of(
        jax.jit(lambda x: x * 2, donate_argnums=(0,)),
        jnp.ones(3, jnp.float32),
    )
    assert sig["donated_args"] == 1
    assert sig["inputs"] == ["float32[3]"]
    assert sig["f64_avals"] == 0
    assert "mul" in sig["prims"]


def test_injected_transfer_in_wrapped_step_fires():
    """Regression injection: a 'helpful' jax.device_put inside a
    wrapped walk step — io.transfers must name it."""
    from pumiumtally_tpu.ops import walk

    mesh, a = _tiny_problem()

    def wrapped(origin, dest, elem, fly, w, g, mat, flux):
        flux = jax.device_put(flux)  # the injected contract break
        return walk.trace_impl(
            mesh, origin, dest, elem, fly, w, g, mat, flux,
            **_tiny_statics(),
        )

    sig = _sig_of(
        jax.jit(wrapped, donate_argnums=(7,)),
        a["origin"], a["dest"], a["elem"], a["in_flight"],
        a["weight"], a["group"], a["material_id"], a["flux"],
    )
    assert sig["prims"].get("device_put", 0) >= 1
    syms = [f.symbol for f in _structural("trace_packed", sig)]
    assert "io.transfers.trace_packed" in syms


def test_injected_host_callback_fires():
    """Regression injection: a host peek (the traceable analogue of a
    device_get mid-step) — io.callbacks must name it."""
    from pumiumtally_tpu.ops import walk

    mesh, a = _tiny_problem()

    def wrapped(origin, dest, elem, fly, w, g, mat, flux):
        r = walk.trace_impl(
            mesh, origin, dest, elem, fly, w, g, mat, flux,
            **_tiny_statics(),
        )
        peeked = jax.pure_callback(
            lambda v: np.asarray(v), jax.ShapeDtypeStruct((), r.flux.dtype),
            r.n_segments.astype(r.flux.dtype),
        )
        return r._replace(n_segments=peeked.astype(r.n_segments.dtype))

    sig = _sig_of(
        jax.jit(wrapped, donate_argnums=(7,)),
        a["origin"], a["dest"], a["elem"], a["in_flight"],
        a["weight"], a["group"], a["material_id"], a["flux"],
    )
    syms = [f.symbol for f in _structural("trace", sig)]
    assert "io.callbacks.trace" in syms


def test_injected_dropped_donation_fires():
    """Regression injection: re-jitting the step WITHOUT donation —
    donation.<family> must fire."""
    from pumiumtally_tpu.ops import walk

    mesh, a = _tiny_problem()

    def plain(origin, dest, elem, fly, w, g, mat, flux):
        return walk.trace_impl(
            mesh, origin, dest, elem, fly, w, g, mat, flux,
            **_tiny_statics(),
        )

    sig = _sig_of(
        jax.jit(plain),  # donation dropped
        a["origin"], a["dest"], a["elem"], a["in_flight"],
        a["weight"], a["group"], a["material_id"], a["flux"],
    )
    assert sig["donated_args"] == 0
    syms = [f.symbol for f in _structural("trace", sig)]
    assert "donation.trace" in syms


def test_injected_f64_leak_fires():
    sig = _sig_of(
        jax.jit(
            lambda x: (x.astype(jnp.float64) * 2).astype(x.dtype),
            donate_argnums=(0,),
        ),
        jnp.ones(3, jnp.float32),
    )
    assert sig["f64_avals"] > 0 and sig["convert_to_f64"] >= 1
    syms = [f.symbol for f in _structural("trace", sig)]
    assert "dtype.f32_purity.trace" in syms


def test_degraded_scan_fires_on_megastep():
    from pumiumtally_tpu.analysis.contracts import check_structural

    sig = _sig_of(
        jax.jit(lambda x: x + 1, donate_argnums=(0,)),
        jnp.ones(3, jnp.float32),
    )  # no scan anywhere
    syms = [
        f.symbol
        for f in check_structural({"families": {"megastep": sig}})
    ]
    assert "structure.scan.megastep" in syms
    assert "structure.scatter.megastep" in syms


def test_real_trace_family_satisfies_structural_invariants():
    from pumiumtally_tpu.analysis import contracts as C

    traced = C.build_traced(families=("trace",))
    sigs = {
        "environment": C.environment(),
        "families": {k: C.extract_signature(v) for k, v in traced.items()},
    }
    # Under the x64 test env the f64 census is not meaningful; the
    # transfer/callback/donation/structure halves must hold everywhere.
    findings = [
        f
        for f in C.check_structural(sigs)
        if not f.symbol.startswith("dtype.")
    ]
    assert findings == [], [f.symbol for f in findings]


def test_diff_baseline_names_drift():
    from pumiumtally_tpu.analysis import contracts as C

    traced = C.build_traced(families=("trace",))
    cap = {
        "environment": C.environment(),
        "families": {k: C.extract_signature(v) for k, v in traced.items()},
    }
    base = json.loads(json.dumps(cap))  # deep copy
    assert C.diff_baseline(cap, base) == []

    tampered = json.loads(json.dumps(base))
    fam = tampered["families"]["trace"]
    fam["prims"]["scatter-add"] = fam["prims"].get("scatter-add", 0) + 1
    fam["donated_args"] = 0
    fam["inputs"] = fam["inputs"][:-1]
    syms = {f.symbol for f in C.diff_baseline(cap, tampered)}
    assert "prims.scatter-add.trace" in syms
    assert "signature.donated_args.trace" in syms
    assert "signature.inputs.trace" in syms

    other_env = json.loads(json.dumps(base))
    other_env["environment"]["x64"] = not other_env["environment"]["x64"]
    syms = {f.symbol for f in C.diff_baseline(cap, other_env)}
    assert syms == {"environment.all"}


def _tiny_problem():
    from pumiumtally_tpu.analysis.contracts import _problem

    return _problem(jnp.float32)


def _tiny_statics():
    from pumiumtally_tpu.analysis.contracts import _walk_statics

    return _walk_statics()


# --------------------------------------------------------------------- #
# End to end: the committed baseline matches the committed programs
# --------------------------------------------------------------------- #
def test_lint_runner_exits_clean():
    """scripts/lint.py (fresh process: canonical cpu/8-device/x64-off
    environment) must exit 0 against the committed CONTRACTS.json and
    LINT_BASELINE.json — zero non-baselined findings in the repo."""
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)  # the runner pins its own
    proc = subprocess.run(
        [sys.executable, str(ROOT / "scripts" / "lint.py")],
        capture_output=True, text=True, env=env, cwd=str(ROOT),
        timeout=300,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
