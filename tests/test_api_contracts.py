"""Facade contract tests: out-param validation, group-bounds rejection,
walk-truncation reporting, element-sort layout transparency, legacy VTK."""
import warnings

import jax.numpy as jnp
import numpy as np
import pytest

from pumiumtally_tpu import PumiTally, TallyConfig, build_box


def _mk(n=3, **cfg_kw):
    cfg = TallyConfig(dtype=jnp.float64, **cfg_kw)
    t = PumiTally(build_box(dtype=jnp.float64), n, cfg)
    pos = np.tile([0.5, 0.6, 0.4], n)
    t.initialize_particle_location(pos, pos.size)
    return t


def _move_args(n, dest_xyz=(0.6, 0.6, 0.4)):
    return (
        np.tile(np.asarray(dest_xyz, dtype=np.float64), n),
        np.ones(n, dtype=np.int8),
        np.ones(n),
        np.zeros(n, dtype=np.int32),
        np.zeros(n, dtype=np.int32),
    )


def test_out_params_must_be_ndarrays():
    t = _mk()
    dest, flying, w, g, m = _move_args(3)
    with pytest.raises(TypeError, match="flying"):
        t.move_to_next_location(dest, [1, 1, 1], w, g, m, dest.size)
    with pytest.raises(TypeError, match="particle_destinations"):
        t.move_to_next_location(dest.tolist(), flying, w, g, m, dest.size)
    with pytest.raises(TypeError, match="material_ids"):
        t.move_to_next_location(
            dest, flying, w, g, m.astype(np.int64), dest.size
        )


def test_non_contiguous_out_param_rejected():
    t = _mk()
    dest, flying, w, g, m = _move_args(3)
    big = np.zeros((6, 4))
    strided = big[::2, :3]  # 3x3 view that reshape(-1) cannot flatten in place
    with pytest.raises(ValueError, match="contiguous"):
        t.move_to_next_location(strided, flying, w, g, m, 9)


def test_group_out_of_range_rejected():
    # The reference hard-asserts group bounds on device (cpp:634-638).
    t = _mk()
    dest, flying, w, _, m = _move_args(3)
    bad = np.array([0, 5, 0], dtype=np.int32)
    with pytest.raises(ValueError, match="energy group"):
        t.move_to_next_location(dest, flying, w, bad, m, dest.size)
    bad = np.array([0, -1, 0], dtype=np.int32)
    with pytest.raises(ValueError, match="energy group"):
        t.move_to_next_location(dest, flying, w, bad, m, dest.size)


def test_truncated_walk_warns():
    # An anisotropic 40x1x1 box with a max_crossings too small for the long
    # axis: the walk must report truncation, not silently stop mid-domain.
    cfg = TallyConfig(dtype=jnp.float64, max_crossings=8)
    mesh = build_box(40.0, 1.0, 1.0, 40, 1, 1, dtype=jnp.float64)
    t = PumiTally(mesh, 1, cfg)
    t.initialize_particle_location(np.array([0.05, 0.4, 0.5]), 3)
    dest, flying, w, g, m = _move_args(1, dest_xyz=(39.95, 0.4, 0.5))
    with pytest.warns(RuntimeWarning, match="truncated"):
        t.move_to_next_location(dest, flying, w, g, m, dest.size)


def test_default_max_crossings_handles_long_anisotropic_mesh():
    mesh = build_box(40.0, 1.0, 1.0, 40, 1, 1, dtype=jnp.float64)
    t = PumiTally(mesh, 1, TallyConfig(dtype=jnp.float64))
    t.initialize_particle_location(np.array([0.05, 0.4, 0.5]), 3)
    dest, flying, w, g, m = _move_args(1, dest_xyz=(39.95, 0.4, 0.5))
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        t.move_to_next_location(dest, flying, w, g, m, dest.size)
    # Full track length scored.
    assert t.raw_flux[:, 0, 0].sum() == pytest.approx(39.9, abs=1e-8)
    np.testing.assert_allclose(
        dest.reshape(1, 3), [[39.95, 0.4, 0.5]], atol=1e-8
    )


def test_sort_by_element_preserves_host_order():
    # Same random walk with and without the locality sort: identical host
    # observables (the migrate analog must be invisible to the caller).
    n = 16
    rng = np.random.default_rng(3)
    starts = rng.uniform(0.1, 0.9, (n, 3))

    results = []
    for sort in (False, True):
        t = _mk(n=n, sort_by_element=sort, migration_period=1)
        t.initialize_particle_location(starts.ravel().copy(), n * 3)
        prev = starts.copy()
        for step in range(4):
            step_rng = np.random.default_rng(100 + step)
            dest = prev + step_rng.normal(scale=0.3, size=(n, 3))
            buf = np.ascontiguousarray(dest.ravel())
            flying = np.ones(n, dtype=np.int8)
            mats = np.zeros(n, dtype=np.int32)
            t.move_to_next_location(
                buf,
                flying,
                np.ones(n),
                np.zeros(n, np.int32),
                mats,
                buf.size,
            )
            prev = buf.reshape(n, 3).copy()
        results.append(
            (prev, t.element_ids.copy(), t.raw_flux.copy())
        )
    np.testing.assert_allclose(results[0][0], results[1][0], atol=1e-12)
    np.testing.assert_array_equal(results[0][1], results[1][1])
    np.testing.assert_allclose(results[0][2], results[1][2], atol=1e-12)


def test_legacy_vtk_extension_writes_legacy_format(tmp_path):
    t = _mk()
    dest, flying, w, g, m = _move_args(3)
    t.move_to_next_location(dest, flying, w, g, m, dest.size)
    out = t.write_pumi_tally_mesh(str(tmp_path / "fluxresult.vtk"))
    head = open(out).readline()
    assert head.startswith("# vtk DataFile")
    text = open(out).read()
    assert "flux_group_0" in text and "CELL_DATA" in text
