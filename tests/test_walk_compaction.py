"""Straggler compaction must be a pure scheduling change: identical results
to the flat while_loop for every output, including heterogeneous ray
lengths, parked particles, boundary clips, and multi-round tails."""
import jax.numpy as jnp
import numpy as np
import pytest

from pumiumtally_tpu import build_box, make_flux, trace
from pumiumtally_tpu.ops.geometry import locate_points


@pytest.mark.parametrize(
    "compact_size",
    [pytest.param(8, marks=pytest.mark.slow), 32,
     pytest.param(None, marks=pytest.mark.slow)],
)
def test_compaction_matches_flat(compact_size):
    mesh = build_box(1, 1, 1, 4, 4, 4, dtype=jnp.float64)
    n = 128
    rng = np.random.default_rng(5)
    origin = rng.uniform(0.05, 0.95, (n, 3))
    # Mix of short hops, long diagonals (straggler tail), and out-of-domain.
    dest = origin + rng.normal(scale=0.05, size=(n, 3))
    dest[: n // 4] = rng.uniform(-0.5, 1.5, (n // 4, 3))
    in_flight = (rng.random(n) > 0.2)
    weight = rng.uniform(0.1, 3.0, n)
    group = rng.integers(0, 2, n)
    elem = np.asarray(locate_points(mesh, jnp.asarray(origin), 1e-12))
    assert (elem >= 0).all()

    args = dict(
        initial=False,
        max_crossings=mesh.ntet + 64,
        tolerance=1e-12,
    )
    common = (
        mesh,
        jnp.asarray(origin),
        jnp.asarray(dest),
        jnp.asarray(elem, jnp.int32),
        jnp.asarray(in_flight),
        jnp.asarray(weight),
        jnp.asarray(group, jnp.int32),
        jnp.full(n, -1, jnp.int32),
    )
    flat = trace(*common, make_flux(mesh.ntet, 2, jnp.float64), **args)
    compact = trace(
        *common,
        make_flux(mesh.ntet, 2, jnp.float64),
        compact_after=2,
        compact_size=compact_size,
        **args,
    )

    np.testing.assert_allclose(
        np.asarray(compact.position), np.asarray(flat.position), atol=1e-14
    )
    np.testing.assert_array_equal(
        np.asarray(compact.elem), np.asarray(flat.elem)
    )
    np.testing.assert_array_equal(
        np.asarray(compact.material_id), np.asarray(flat.material_id)
    )
    np.testing.assert_allclose(
        np.asarray(compact.flux), np.asarray(flat.flux), atol=1e-12
    )
    assert int(compact.n_segments) == int(flat.n_segments)
    assert bool(np.asarray(compact.done).all())


def test_compaction_with_truncation_reports_not_done():
    mesh = build_box(20.0, 1.0, 1.0, 20, 1, 1, dtype=jnp.float64)
    n = 4
    origin = np.tile([0.05, 0.4, 0.5], (n, 1))
    dest = np.tile([19.95, 0.4, 0.5], (n, 1))
    elem = np.asarray(locate_points(mesh, jnp.asarray(origin), 1e-12))
    r = trace(
        mesh,
        jnp.asarray(origin),
        jnp.asarray(dest),
        jnp.asarray(elem, jnp.int32),
        jnp.ones(n, bool),
        jnp.ones(n),
        jnp.zeros(n, jnp.int32),
        jnp.full(n, -1, jnp.int32),
        make_flux(mesh.ntet, 2, jnp.float64),
        initial=False,
        max_crossings=10,  # far below the ~100 crossings needed
        compact_after=2,
        compact_size=2,
    )
    assert not bool(np.asarray(r.done).any())


@pytest.mark.parametrize(
    "sched",
    [
        dict(compact_after=2, compact_size=16),
        pytest.param(
            dict(compact_stages=((2, 32), (6, 16), (10, 8))),
            marks=pytest.mark.slow,
        ),
    ],
)
def test_record_xpoints_composes_with_compaction(sched):
    """Intersection-point recording must survive the straggler
    gather/scatter-back: the compacted walk records exactly the flat
    walk's points and counts (the xp/kx lanes ride compaction rounds
    like any other per-particle state), so the production config
    (compact_stages="auto") can record too — reference tracer's
    getIntersectionPoints() is unconditional (test:403-479)."""
    mesh = build_box(1, 1, 1, 4, 4, 4, dtype=jnp.float64)
    n = 128
    rng = np.random.default_rng(11)
    origin = rng.uniform(0.05, 0.95, (n, 3))
    dest = origin + rng.normal(scale=0.05, size=(n, 3))
    dest[: n // 4] = rng.uniform(-0.5, 1.5, (n // 4, 3))
    in_flight = rng.random(n) > 0.2
    weight = rng.uniform(0.1, 3.0, n)
    group = rng.integers(0, 2, n)
    elem = np.asarray(locate_points(mesh, jnp.asarray(origin), 1e-12))
    assert (elem >= 0).all()

    args = dict(
        initial=False,
        max_crossings=mesh.ntet + 64,
        tolerance=1e-12,
        record_xpoints=6,
    )
    common = (
        mesh,
        jnp.asarray(origin),
        jnp.asarray(dest),
        jnp.asarray(elem, jnp.int32),
        jnp.asarray(in_flight),
        jnp.asarray(weight),
        jnp.asarray(group, jnp.int32),
        jnp.full(n, -1, jnp.int32),
    )
    flat = trace(*common, make_flux(mesh.ntet, 2, jnp.float64), **args)
    compact = trace(
        *common, make_flux(mesh.ntet, 2, jnp.float64), **sched, **args
    )

    assert bool(np.asarray(compact.done).all())
    np.testing.assert_array_equal(
        np.asarray(compact.n_xpoints), np.asarray(flat.n_xpoints)
    )
    # Recorded points: identical where recorded; slots past a lane's
    # count are never written in either schedule (both zero-initialized).
    np.testing.assert_allclose(
        np.asarray(compact.xpoints), np.asarray(flat.xpoints), atol=1e-14
    )
    np.testing.assert_allclose(
        np.asarray(compact.flux), np.asarray(flat.flux), atol=1e-12
    )
    assert int(np.asarray(flat.n_xpoints).max()) >= 3  # scenario non-trivial


def test_sparse_schedule_big_unroll_warns():
    """Per-stage unroll >= 16 on a sparse (<6 stage) schedule measured
    ~35x slower on TPU (round-4 grid, tail64_96_u32: 0.21 vs 7.6
    Mseg/s); normalize_compact_stages must flag the shape before a user
    burns a hardware window on it."""
    from pumiumtally_tpu.ops.walk import normalize_compact_stages

    sparse_u32 = ((16, 512), (24, 256), (40, 128), (64, 64, 16),
                  (96, 32, 32))
    with pytest.warns(RuntimeWarning, match="35x"):
        normalize_compact_stages(sparse_u32, None, None, 1024, 128)

    # The dense-ladder shape (>= 6 stages) with the same tail unrolls
    # measured neutral (dense_u32tail 7.62 vs dense 7.60) — no warning.
    dense_u = ((8, 640), (16, 384), (24, 256), (32, 128),
               (48, 64, 16), (64, 32, 16), (96, 16, 32))
    import warnings as _w
    with _w.catch_warnings():
        _w.simplefilter("error")
        normalize_compact_stages(dense_u, None, None, 1024, 128)

    # Small unrolls on sparse schedules stay silent too.
    with _w.catch_warnings():
        _w.simplefilter("error")
        normalize_compact_stages(((16, 512), (64, 64, 8)), None, None,
                                 1024, 128)


def test_nonpositive_stage_size_rejected():
    from pumiumtally_tpu.ops.walk import normalize_compact_stages

    with pytest.raises(ValueError, match=">= 1"):
        normalize_compact_stages(((16, 0),), None, None, 1024, 128)
    with pytest.raises(ValueError, match=">= 1"):
        normalize_compact_stages(((16, 64, 0),), None, None, 1024, 128)
    with pytest.raises(ValueError, match=">= 1"):
        # The compact_after/compact_size fold must hit the same check.
        normalize_compact_stages(None, 10, 0, 1024, 128)
