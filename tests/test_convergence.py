"""Statistical convergence observability (obs/convergence.py,
TallyConfig.convergence).

Pinned contracts:

  * ORACLE — the fused on-device reduction (rel-err mean/max, converged
    fraction) and ``relative_error()`` match an independent NumPy
    float64 batch-statistics oracle built from per-move accumulator
    snapshots, on jittered meshes, across {f32, f64} x {legacy, packed,
    overlap}, on both facades.
  * READ-ONLY — with convergence ON, flux / copied-back positions /
    material ids are BIT-identical to a convergence-off run (the
    reductions read, never write), and a packed steady-state move still
    issues exactly ONE H2D and ONE D2H transfer.
  * EARLY STOP — ``converged()`` flips exactly at the analytically
    expected batch count on a deterministic fixed-seed problem.
  * SATELLITES — the Prometheus scrape endpoint, the thread-safe flight
    recorder, and the metrics lint (non-empty help, no conflicting
    re-registration).
"""
from __future__ import annotations

import threading
import urllib.request

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from pumiumtally_tpu import PumiTally, TallyConfig, build_box
from pumiumtally_tpu.mesh.box import build_box_arrays
from pumiumtally_tpu.mesh.core import TetMesh
from pumiumtally_tpu.obs import FlightRecorder, MetricsRegistry
from pumiumtally_tpu.obs.exporter import MetricsExporter
from pumiumtally_tpu.parallel.partitioned_api import PartitionedTally

N = 96
TARGET = 0.3  # one rel_err_target everywhere → one compiled signature


def _jittered_mesh(dtype, nx=4, jitter=0.2, seed=11):
    coords, tets = build_box_arrays(1.0, 1.0, 1.0, nx, nx, nx)
    rng = np.random.default_rng(seed)
    h = 1.0 / nx
    interior = (
        (coords > 1e-9).all(axis=1) & (coords < 1 - 1e-9).all(axis=1)
    )
    coords = coords.copy()
    coords[interior] += rng.uniform(
        -jitter * h, jitter * h, (int(interior.sum()), 3)
    )
    cid = (coords[tets].mean(axis=1)[:, 0] > 0.5).astype(np.int32) + 1
    return TetMesh.from_numpy(coords, tets, cid, dtype=dtype)


@pytest.fixture(scope="module")
def mesh64():
    return _jittered_mesh(jnp.float64)


def _cfg(dtype=jnp.float64, io="packed", **kw):
    tol = 1e-8 if dtype == jnp.float64 else 1e-6
    kw.setdefault("convergence", True)
    kw.setdefault("rel_err_target", TARGET)
    return TallyConfig(
        n_groups=2, dtype=dtype, tolerance=tol, io_pipeline=io, **kw
    )


def _drive(t, moves=4, seed=17, evens=None):
    """The test driver of test_io_pipeline, plus optional per-move even
    (Σc) accumulator snapshots for the host oracle."""
    rng = np.random.default_rng(seed)
    n = t.num_particles
    pos = rng.uniform(0.05, 0.95, (n, 3))
    t.initialize_particle_location(pos.ravel().copy(), n * 3)
    outs, prev = [], pos
    for _ in range(moves):
        dest = np.clip(prev + rng.normal(0, 0.25, (n, 3)), -0.1, 1.1)
        buf = dest.ravel().copy()
        flying = np.ones(n, np.int8)
        flying[::7] = 0  # parked lanes ride along
        w = rng.uniform(0.5, 2.0, n)
        g = rng.integers(0, 2, n).astype(np.int32)
        mats = np.full(n, 9, np.int32)
        t.move_to_next_location(buf, flying, w, g, mats, buf.size)
        outs.append((buf.reshape(n, 3).copy(), mats.copy()))
        if evens is not None:
            evens.append(
                t.raw_flux[..., 0].astype(np.float64).reshape(-1)
            )
        prev = buf.reshape(n, 3).copy()
    return outs


def _oracle(evens, target=TARGET):
    """Independent float64 batch-statistics oracle from the per-move
    even-accumulator snapshots (batch_moves=1: every move one batch)."""
    snaps = np.stack([np.zeros_like(evens[0])] + list(evens))
    T = np.diff(snaps, axis=0)  # [B, nbins] per-batch bin totals
    B = T.shape[0]
    s1, s2 = T.sum(0), (T * T).sum(0)
    scored = s1 > 0
    rel = np.where(
        scored,
        np.sqrt(np.maximum(B * s2 - s1 * s1, 0.0) / max(B - 1, 1))
        / np.where(scored, s1, 1.0),
        0.0,
    )
    if B < 2:
        rel = np.where(scored, 1.0, 0.0)
    return {
        "n_batches": B,
        "scored": int(scored.sum()),
        "rel": rel,
        "rel_err_mean": float(rel.sum() / max(scored.sum(), 1)),
        "rel_err_max": float(rel.max(initial=0.0)),
        "converged_fraction": float(
            (scored & (rel <= target)).sum() / max(scored.sum(), 1)
        ),
    }


# --------------------------------------------------------------------- #
# Oracle parity
# --------------------------------------------------------------------- #
@pytest.mark.parametrize(
    "dtype,io,rtol",
    [
        (jnp.float64, "legacy", 1e-9),
        (jnp.float64, "packed", 1e-9),
        (jnp.float64, "overlap", 1e-9),
        (jnp.float32, "packed", 3e-2),
    ],
)
def test_single_chip_matches_float64_oracle(dtype, io, rtol, monkeypatch):
    monkeypatch.delenv("PUMI_TPU_IO_PIPELINE", raising=False)
    mesh = _jittered_mesh(dtype)
    t = PumiTally(mesh, N, _cfg(dtype, io))
    evens = []
    _drive(t, moves=4, evens=evens)
    want = _oracle(evens)
    got = t.telemetry()["convergence"]
    assert got["enabled"] and got["n_batches"] == want["n_batches"]
    assert got["scored"] == want["scored"]
    np.testing.assert_allclose(
        got["rel_err_mean"], want["rel_err_mean"], rtol=rtol
    )
    np.testing.assert_allclose(
        got["rel_err_max"], want["rel_err_max"], rtol=rtol
    )
    # The converged fraction counts threshold crossings: f32 accumulators
    # may flip bins sitting ON the threshold — bound the disagreement by
    # the near-threshold population instead of demanding bit equality.
    near = int(
        (np.abs(want["rel"] - TARGET) < 1e3 * rtol * TARGET).sum()
    )
    assert (
        abs(
            got["converged_fraction"] * got["scored"]
            - want["converged_fraction"] * want["scored"]
        )
        <= near
    )
    assert got["fom"] > 0
    # relative_error() is the same estimator materialized per bin.
    np.testing.assert_allclose(
        t.relative_error().reshape(-1), want["rel"],
        rtol=rtol, atol=rtol,
    )


@pytest.mark.parametrize("io", ["legacy", "packed"])
def test_partitioned_matches_float64_oracle_and_single_chip(
    mesh64, io, monkeypatch
):
    monkeypatch.delenv("PUMI_TPU_IO_PIPELINE", raising=False)
    t = PartitionedTally(
        mesh64, N, _cfg(io=io), n_parts=4, halo_layers=1
    )
    evens = []
    _drive(t, moves=3, evens=evens)
    want = _oracle(evens)
    got = t.telemetry()["convergence"]
    assert got["n_batches"] == want["n_batches"]
    assert got["scored"] == want["scored"]
    np.testing.assert_allclose(
        got["rel_err_mean"], want["rel_err_mean"], rtol=1e-9
    )
    np.testing.assert_allclose(
        got["rel_err_max"], want["rel_err_max"], rtol=1e-9
    )
    np.testing.assert_allclose(
        t.relative_error().reshape(-1), want["rel"],
        rtol=1e-9, atol=1e-12,
    )
    # Cross-facade agreement: same problem through the single-chip walk.
    s = PumiTally(mesh64, N, _cfg())
    _drive(s, moves=3)
    ref = s.telemetry()["convergence"]
    assert got["scored"] == ref["scored"]
    assert got["n_batches"] == ref["n_batches"]
    np.testing.assert_allclose(
        got["rel_err_mean"], ref["rel_err_mean"], rtol=1e-9
    )
    np.testing.assert_allclose(
        got["rel_err_max"], ref["rel_err_max"], rtol=1e-9
    )


# --------------------------------------------------------------------- #
# Read-only + transfer-count invariants
# --------------------------------------------------------------------- #
def test_outputs_bit_identical_with_convergence_on(mesh64, monkeypatch):
    monkeypatch.delenv("PUMI_TPU_IO_PIPELINE", raising=False)
    a = PumiTally(mesh64, N, _cfg(convergence=False))
    b = PumiTally(mesh64, N, _cfg())
    outs_a, outs_b = _drive(a, moves=3), _drive(b, moves=3)
    for (pa, ma), (pb, mb) in zip(outs_a, outs_b):
        np.testing.assert_array_equal(pb, pa)
        np.testing.assert_array_equal(mb, ma)
    np.testing.assert_array_equal(b.raw_flux, a.raw_flux)
    np.testing.assert_array_equal(b.element_ids, a.element_ids)

    c = PartitionedTally(
        mesh64, N, _cfg(convergence=False), n_parts=4, halo_layers=1
    )
    d = PartitionedTally(mesh64, N, _cfg(), n_parts=4, halo_layers=1)
    outs_c, outs_d = _drive(c, moves=2), _drive(d, moves=2)
    for (pc, mc), (pd, md) in zip(outs_c, outs_d):
        np.testing.assert_array_equal(pd, pc)
        np.testing.assert_array_equal(md, mc)
    np.testing.assert_array_equal(d.raw_flux, c.raw_flux)


def _io_totals(t):
    totals = t.telemetry()["totals"]
    return totals["h2d_transfers"], totals["d2h_transfers"]


def _move(t, dest, seed=3):
    rng = np.random.default_rng(seed)
    n = t.num_particles
    buf = dest.ravel().copy()
    t.move_to_next_location(
        buf, np.ones(n, np.int8), rng.uniform(0.5, 2.0, n),
        rng.integers(0, 2, n).astype(np.int32), np.full(n, -1, np.int32),
    )
    return buf


def test_steady_state_one_transfer_each_way_with_convergence(monkeypatch):
    """The acceptance invariant: with convergence ON, a packed
    steady-state move still performs exactly 1 H2D + 1 D2H (the summary
    rides the readback tail; the batch state never leaves the device)."""
    monkeypatch.delenv("PUMI_TPU_IO_PIPELINE", raising=False)
    mesh = build_box(1.0, 1.0, 1.0, 3, 3, 3)
    t = PumiTally(
        mesh, 64,
        TallyConfig(
            tolerance=1e-6, io_pipeline="packed", convergence=True,
            rel_err_target=TARGET,
        ),
    )
    rng = np.random.default_rng(0)
    t.initialize_particle_location(rng.uniform(0.1, 0.9, (64, 3)).ravel())
    _move(t, rng.uniform(0.1, 0.9, (64, 3)), seed=1)  # warm/compile
    h0, d0 = _io_totals(t)
    with jax.transfer_guard("disallow"):
        _move(t, rng.uniform(0.1, 0.9, (64, 3)), seed=2)
    h1, d1 = _io_totals(t)
    assert (h1 - h0, d1 - d0) == (1, 1)


def test_partitioned_steady_state_transfers_with_convergence(
    mesh64, monkeypatch
):
    monkeypatch.delenv("PUMI_TPU_IO_PIPELINE", raising=False)
    t = PartitionedTally(mesh64, N, _cfg(), n_parts=4, halo_layers=1)
    rng = np.random.default_rng(0)
    t.initialize_particle_location(rng.uniform(0.1, 0.9, (N, 3)).ravel())
    _move(t, rng.uniform(0.1, 0.9, (N, 3)), seed=1)  # warm/compile
    h0, d0 = _io_totals(t)
    with jax.transfer_guard("disallow"):
        _move(t, rng.uniform(0.1, 0.9, (N, 3)), seed=2)
    h1, d1 = _io_totals(t)
    assert (h1 - h0, d1 - d0) == (1, 1)


# --------------------------------------------------------------------- #
# Early stop, cadence, explicit batches
# --------------------------------------------------------------------- #
def test_converged_flips_at_expected_batch_count():
    """Deterministic shuttle: each move retraces the same chord, so
    every batch's bin totals are (fp-)identical → rel-err ≈ 0 from the
    FIRST moment it is defined.  The estimator needs 2 batches for a
    variance, so converged() must flip exactly at batch 2."""
    mesh = build_box(1.0, 1.0, 1.0, 3, 3, 3, dtype=jnp.float64)
    n = 8
    t = PumiTally(
        mesh, n,
        TallyConfig(
            dtype=jnp.float64, tolerance=1e-8, convergence=True,
            rel_err_target=0.01, converged_fraction=1.0,
        ),
    )
    rng = np.random.default_rng(5)
    a = rng.uniform(0.15, 0.45, (n, 3))
    b = a + 0.35  # fixed chords, interior, single material region
    t.initialize_particle_location(a.ravel().copy())
    ends = [b, a]
    for move in range(4):
        dest = ends[move % 2]
        buf = dest.ravel().copy()
        t.move_to_next_location(
            buf, np.ones(n, np.int8), np.ones(n),
            np.zeros(n, np.int32), np.full(n, -1, np.int32),
        )
        assert t.converged() == (move + 1 >= 2), (
            f"converged() after move {move + 1}"
        )
    conv = t.telemetry()["convergence"]
    assert conv["n_batches"] == 4
    # Forward and backward traversals of the same chord agree to fp
    # accumulation (the robust walk's unscored ulp-scale bumps make the
    # two directions a few 1e-9 apart, not bitwise) — far below target.
    assert conv["rel_err_max"] <= 1e-6
    assert conv["converged_fraction"] == 1.0


def test_batch_moves_cadence_and_explicit_end_batch(mesh64, monkeypatch):
    monkeypatch.delenv("PUMI_TPU_IO_PIPELINE", raising=False)
    t = PumiTally(mesh64, N, _cfg(batch_moves=3))
    evens = []
    _drive(t, moves=4, evens=evens)
    conv = t.telemetry()["convergence"]
    # Moves 1-3 close batch 1; move 4 is mid-batch.
    assert conv["n_batches"] == 1 and conv["batch_moves"] == 3
    out = t.end_batch()  # closes the 1-move partial batch now
    assert out["n_batches"] == 2
    assert t.telemetry()["convergence"]["n_batches"] == 2
    # The explicit close folded exactly the move-4 delta: 2 batches of
    # totals (moves 1-3, move 4) — pin against the oracle.
    snaps = np.stack(
        [np.zeros_like(evens[0]), evens[2], evens[3]]
    )
    T = np.diff(snaps, axis=0)
    s1, s2 = T.sum(0), (T * T).sum(0)
    scored = s1 > 0
    rel = np.where(
        scored,
        np.sqrt(np.maximum(2 * s2 - s1 * s1, 0.0)) / np.where(
            scored, s1, 1.0
        ),
        0.0,
    )
    np.testing.assert_allclose(
        out["rel_err_max"], rel.max(), rtol=1e-9
    )
    # The explicit close restarted the cadence: 2 further moves stay
    # mid-batch, the 3rd closes batch 3.
    _continue(t, 2)
    assert t.telemetry()["convergence"]["n_batches"] == 2
    _continue(t, 1, seed=29)
    assert t.telemetry()["convergence"]["n_batches"] == 3


def _continue(t, moves, seed=23):
    rng = np.random.default_rng(seed)
    n = t.num_particles
    for _ in range(moves):
        dest = rng.uniform(0.05, 0.95, (n, 3))
        buf = dest.ravel().copy()
        t.move_to_next_location(
            buf, np.ones(n, np.int8), np.ones(n),
            np.zeros(n, np.int32), np.full(n, 9, np.int32),
        )


def test_checkpoint_restore_rebases_batch_statistics(
    mesh64, tmp_path, monkeypatch
):
    monkeypatch.delenv("PUMI_TPU_IO_PIPELINE", raising=False)
    a = PumiTally(mesh64, N, _cfg())
    _drive(a, moves=3)
    assert a.telemetry()["convergence"]["n_batches"] == 3
    ck = str(tmp_path / "conv.npz")
    a.save_checkpoint(ck)
    b = PumiTally(mesh64, N, _cfg())
    b.restore_checkpoint(ck)
    # Batch history is monitor state, not resumable tally state: the
    # restored run re-bases on the restored accumulator and restarts.
    conv = b.telemetry()["convergence"]
    assert conv["n_batches"] == 0 and not b.converged()
    _continue(b, 2)
    assert b.telemetry()["convergence"]["n_batches"] == 2

    # Partitioned facade: same re-base contract over the sharded
    # per-chip accumulators.
    c = PartitionedTally(mesh64, N, _cfg(), n_parts=4, halo_layers=1)
    _drive(c, moves=2)
    ckp = str(tmp_path / "conv_part.npz")
    c.save_checkpoint(ckp)
    d = PartitionedTally(mesh64, N, _cfg(), n_parts=4, halo_layers=1)
    d.restore_checkpoint(ckp)
    assert d.telemetry()["convergence"]["n_batches"] == 0
    _continue(d, 1)
    assert d.telemetry()["convergence"]["n_batches"] == 1
    assert d.relative_error().shape == (mesh64.ntet, 2)


# --------------------------------------------------------------------- #
# Uncertainty export + config validation
# --------------------------------------------------------------------- #
def test_vtk_uncertainty_field(mesh64, tmp_path, monkeypatch):
    monkeypatch.delenv("PUMI_TPU_IO_PIPELINE", raising=False)
    t = PumiTally(mesh64, N, _cfg())
    _drive(t, moves=2)
    out = t.write_pumi_tally_mesh(
        str(tmp_path / "flux.vtu"), uncertainty=True
    )
    text = open(out).read()
    assert 'Name="flux_group_0"' in text
    assert 'Name="rel_err_group_0"' in text
    assert 'Name="rel_err_group_1"' in text
    # Without the flag the file stays as before.
    out2 = t.write_pumi_tally_mesh(str(tmp_path / "plain.vtu"))
    assert "rel_err_group" not in open(out2).read()
    # And without convergence the uncertainty export refuses loudly.
    off = PumiTally(mesh64, N, _cfg(convergence=False))
    _drive(off, moves=1)
    with pytest.raises(ValueError, match="convergence"):
        off.write_pumi_tally_mesh(
            str(tmp_path / "no.vtu"), uncertainty=True
        )


def test_config_validation():
    assert TallyConfig().resolve_convergence() is None
    assert TallyConfig(convergence=True).resolve_convergence() == 1
    assert TallyConfig(
        convergence=True, batch_moves=5
    ).resolve_convergence() == 5
    with pytest.raises(ValueError, match="batch_moves"):
        TallyConfig(batch_moves=4).resolve_convergence()
    with pytest.raises(ValueError, match="rel_err_target"):
        TallyConfig(
            convergence=True, rel_err_target=0.0
        ).resolve_convergence()
    with pytest.raises(ValueError, match="converged_fraction"):
        TallyConfig(
            convergence=True, converged_fraction=1.5
        ).resolve_convergence()
    with pytest.raises(ValueError, match="batch_moves"):
        TallyConfig(
            convergence=True, batch_moves=0
        ).resolve_convergence()
    with pytest.raises(ValueError, match="checkify"):
        TallyConfig(
            convergence=True, checkify_invariants=True
        ).resolve_convergence()
    # Off: the API surfaces refuse rather than returning garbage.
    mesh = build_box(1.0, 1.0, 1.0, 2, 2, 2)
    t = PumiTally(mesh, 8, TallyConfig(tolerance=1e-6))
    for call in (t.converged, t.end_batch, t.relative_error):
        with pytest.raises(ValueError, match="convergence"):
            call()


# --------------------------------------------------------------------- #
# Gauges, flight records, scrape endpoint
# --------------------------------------------------------------------- #
def test_gauges_and_per_batch_flight_records(mesh64, monkeypatch):
    monkeypatch.delenv("PUMI_TPU_IO_PIPELINE", raising=False)
    t = PumiTally(mesh64, N, _cfg())
    _drive(t, moves=3)
    text = t.metrics.render_prometheus()
    for name in (
        "pumi_rel_err_max", "pumi_rel_err_mean",
        "pumi_converged_fraction", "pumi_fom", "pumi_batches_total",
    ):
        assert name in text, name
    assert t.metrics.counter("pumi_batches_total").value() == 3
    recs = [
        r for r in t.telemetry()["per_move"]
        if r["kind"] == "convergence"
    ]
    assert [r["batch"] for r in recs] == [1, 2, 3]
    assert all("rel_err_mean" in r and "fom" in r for r in recs)


def _get(url):
    with urllib.request.urlopen(url, timeout=5) as resp:
        return resp.status, resp.headers.get("Content-Type"), \
            resp.read().decode()


def test_exporter_serves_prometheus_text():
    reg = MetricsRegistry()
    reg.counter("demo_total", "a demo counter").inc(3, kind="x")
    exp = MetricsExporter(reg, port=0)
    try:
        status, ctype, body = _get(exp.url)
        assert status == 200 and "version=0.0.4" in ctype
        assert '# HELP demo_total a demo counter' in body
        assert 'demo_total{kind="x"} 3' in body
        status, _, body = _get(exp.url.replace("/metrics", "/healthz"))
        assert status == 200 and body == "ok\n"
        with pytest.raises(urllib.error.HTTPError):
            _get(exp.url.replace("/metrics", "/nope"))
    finally:
        exp.stop()


def test_facade_starts_exporter_from_env(monkeypatch):
    monkeypatch.setenv("PUMI_TPU_PROM_PORT", "0")
    mesh = build_box(1.0, 1.0, 1.0, 2, 2, 2)
    t = PumiTally(mesh, 8, TallyConfig(tolerance=1e-6))
    try:
        assert t._exporter is not None
        url = t._exporter.url
        _, _, body = _get(url)
        assert "pumi_moves_total" in body
    finally:
        t.close()
    # close() released the socket (idempotent) and the port answers no
    # more.
    assert t._exporter is None
    t.close()
    with pytest.raises(Exception):
        _get(url)
    # Unset → no exporter, no thread.
    monkeypatch.delenv("PUMI_TPU_PROM_PORT")
    t2 = PumiTally(mesh, 8, TallyConfig(tolerance=1e-6))
    assert t2.telemetry()["convergence"] == {"enabled": False}
    assert t2._exporter is None
    t2.close()


# --------------------------------------------------------------------- #
# Recorder thread-safety + metrics lint
# --------------------------------------------------------------------- #
def test_flight_recorder_concurrent_records(monkeypatch):
    monkeypatch.delenv("PUMI_TPU_METRICS", raising=False)
    rec = FlightRecorder(capacity=8192)
    n_threads, per = 8, 400

    def work(k):
        for i in range(per):
            rec.record("stress", thread=k, i=i)

    threads = [
        threading.Thread(target=work, args=(k,))
        for k in range(n_threads)
    ]
    for th in threads:
        th.start()
    for th in threads:
        th.join()
    assert rec.total_recorded == n_threads * per
    seqs = [r["seq"] for r in rec.records()]
    # Unique, gap-free sequencing under contention — the PR 4 watchdog
    # records from a worker thread, so this is a real interleaving.
    assert len(set(seqs)) == len(seqs) == n_threads * per
    assert set(seqs) == set(range(n_threads * per))


def test_metrics_lint_help_text(mesh64, monkeypatch):
    """Every metric registered across the obs / resilience / integrity /
    convergence families carries non-empty help text (the scrape
    endpoint's # HELP lines are the operator's only schema)."""
    monkeypatch.delenv("PUMI_TPU_IO_PIPELINE", raising=False)
    t = PumiTally(
        mesh64, N,
        _cfg(
            quarantine=True, integrity="warn", audit_lanes=2,
            truncation_retries=1,
        ),
    )
    _drive(t, moves=2)
    snap = t.metrics.snapshot()
    assert len(snap) >= 20
    missing = [name for name, m in snap.items() if not m["help"]]
    assert not missing, f"metrics without help text: {missing}"
    # And the runner's counters ride the same registry with help.
    from pumiumtally_tpu.resilience.runner import ResilientRunner  # noqa: F401


def test_metrics_lint_no_orphan_serving_registry(tmp_path):
    """Orphan-registry bug class: a serving-path module that registers
    a ``pumi_*`` metric on its OWN registry (instead of the one the
    scheduler's facade/exporter scrapes) increments counters nobody can
    see.  AST-harvest every pumi_* family the serving path declares and
    require each to be reachable from one constructed scheduler's
    registry."""
    import ast
    import os

    from pumiumtally_tpu.serving import TallyScheduler

    pkg = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "pumiumtally_tpu",
    )
    modules = [
        os.path.join(pkg, "serving", "scheduler.py"),
        os.path.join(pkg, "serving", "bank.py"),
        os.path.join(pkg, "resilience", "coordinator.py"),
    ]
    declared: dict[str, str] = {}
    for path in modules:
        with open(path) as fh:
            tree = ast.parse(fh.read(), filename=path)
        for node in ast.walk(tree):
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in ("counter", "gauge", "histogram")
                and node.args
                and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)
                and node.args[0].value.startswith("pumi_")
            ):
                declared[node.args[0].value] = os.path.basename(path)
    # The harvest must see the real serving surface (a refactor that
    # breaks the walk would pass vacuously otherwise).
    assert len(declared) >= 12, sorted(declared)
    mesh = build_box(1.0, 1.0, 1.0, 2, 2, 2)
    sched = TallyScheduler(
        mesh, TallyConfig(tolerance=1e-6),
        bank=str(tmp_path / "bank"), handle_signals=False,
    )
    try:
        reachable = set(sched.registry.snapshot())
    finally:
        sched.close()
    orphans = {
        name: src for name, src in declared.items()
        if name not in reachable
    }
    assert not orphans, (
        f"pumi_* metrics registered on a registry the scheduler's "
        f"scrape endpoint cannot reach: {orphans}"
    )


def test_metrics_lint_no_orphan_fleet_registry(tmp_path):
    """The fleet-layer twin of the orphan-registry lint: every pumi_*
    family the router and the self-healing supervisor declare must be
    reachable from the ONE registry the router's scrape endpoint
    serves (members and supervisor share it by construction)."""
    import ast
    import os

    from pumiumtally_tpu.serving import FleetRouter, FleetSupervisor

    pkg = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "pumiumtally_tpu",
    )
    modules = [
        os.path.join(pkg, "serving", "fleet.py"),
        os.path.join(pkg, "serving", "supervisor.py"),
        # The observability plane registers on the router's registry
        # too — its burn/alert/profile gauges must be scrapeable.
        os.path.join(pkg, "obs", "slo.py"),
        os.path.join(pkg, "obs", "profile.py"),
    ]
    declared: dict[str, str] = {}
    for path in modules:
        with open(path) as fh:
            tree = ast.parse(fh.read(), filename=path)
        for node in ast.walk(tree):
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in ("counter", "gauge", "histogram")
                and node.args
                and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)
                and node.args[0].value.startswith("pumi_")
            ):
                declared[node.args[0].value] = os.path.basename(path)
    # Router (3 families) + supervisor (3 families) at minimum.
    assert len(declared) >= 6, sorted(declared)
    mesh = build_box(1.0, 1.0, 1.0, 2, 2, 2)
    router = FleetRouter(
        mesh, TallyConfig(tolerance=1e-6),
        fleet_dir=str(tmp_path / "fleet"), n_members=1, bank=None,
    )
    try:
        FleetSupervisor(router)
        # The router's scrape surface is /metrics (its own registry)
        # plus /fleetz (member registries folded by the aggregator) —
        # e.g. the profiler reads member-owned quantum/device counters
        # that only the merged view can reach.
        reachable = set(router.registry.snapshot())
        reachable |= set(router.aggregator.merge())
    finally:
        router.close()
    orphans = {
        name: src for name, src in declared.items()
        if name not in reachable
    }
    assert not orphans, (
        f"pumi_* metrics registered on a registry the router's "
        f"scrape endpoints (/metrics + /fleetz) cannot reach: "
        f"{orphans}"
    )


def test_metrics_lint_no_per_job_labels(tmp_path):
    """Cardinality hygiene: a per-job-id label on a counter/gauge/
    histogram makes the family unbounded — every submitted job mints a
    series that lives for the registry's lifetime, and the fleet
    aggregation (obs/aggregate.py) folds ALL of it into /fleetz on
    every scrape.  AST-harvest every label kwarg passed to a metric
    mutation across the serving / obs / resilience surface and ban
    job-identity names outright (per-job data belongs in flight
    records and /jobs, which are capped)."""
    import ast
    import os

    banned = {"job", "job_id", "jobid", "trace_id", "idempotency_key"}
    pkg = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "pumiumtally_tpu",
    )
    offenders = []
    seen_label_kwargs = 0
    for sub in ("serving", "obs", "resilience"):
        folder = os.path.join(pkg, sub)
        for fname in sorted(os.listdir(folder)):
            if not fname.endswith(".py"):
                continue
            path = os.path.join(folder, fname)
            with open(path) as fh:
                tree = ast.parse(fh.read(), filename=path)
            for node in ast.walk(tree):
                if not (
                    isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in ("inc", "set", "observe")
                    and node.keywords
                ):
                    continue
                for kw in node.keywords:
                    if kw.arg is None:
                        continue
                    seen_label_kwargs += 1
                    if kw.arg.lower() in banned:
                        offenders.append(
                            f"{sub}/{fname}:{node.lineno} "
                            f"label {kw.arg!r}"
                        )
    # The harvest must see the real labeled surface (outcome=, member=,
    # source=, ...) or the ban would pass vacuously.
    assert seen_label_kwargs >= 10, seen_label_kwargs
    assert not offenders, (
        f"per-job-identity labels on metric families: {offenders}"
    )


def test_metrics_lint_fleet_merge_has_help(tmp_path):
    """Every family in the fleet-merged snapshot (/fleetz — member
    registries folded with the router's own) carries non-empty help
    text, so the aggregated scrape is as self-describing as the
    per-member one."""
    from pumiumtally_tpu.serving import FleetRouter

    mesh = build_box(1.0, 1.0, 1.0, 2, 2, 2)
    router = FleetRouter(
        mesh, TallyConfig(tolerance=1e-6),
        fleet_dir=str(tmp_path / "fleet"), n_members=2, bank=None,
    )
    try:
        merged = router.aggregator.merge()
    finally:
        router.close()
    assert len(merged) >= 10
    missing = [name for name, m in merged.items() if not m["help"]]
    assert not missing, f"fleet-merged families without help: {missing}"


def test_registry_render_safe_under_concurrent_registration():
    """The scrape thread renders while the move loop lazily registers
    (e.g. the fault counters on first injection): iteration must run
    over a stable copy, not the live family dict."""
    reg = MetricsRegistry()
    stop = threading.Event()
    errs = []

    def reader():
        while not stop.is_set():
            try:
                reg.render_prometheus()
                reg.snapshot()
            except Exception as e:  # pragma: no cover - the regression
                errs.append(e)
                return

    th = threading.Thread(target=reader)
    th.start()
    try:
        for i in range(400):
            reg.counter(f"pumi_stress_{i}_total", "stress family").inc()
    finally:
        stop.set()
        th.join()
    assert not errs, errs


def test_registry_rejects_conflicting_reregistration():
    reg = MetricsRegistry()
    c = reg.counter("pumi_thing_total", "what it counts")
    assert reg.counter("pumi_thing_total", "what it counts") is c
    assert reg.counter("pumi_thing_total") is c  # help-less lookup
    with pytest.raises(ValueError, match="conflicting help"):
        reg.counter("pumi_thing_total", "a different meaning")
    with pytest.raises(ValueError, match="already registered as"):
        reg.gauge("pumi_thing_total", "what it counts")
