"""Serving-under-failure contracts (the ISSUE 14 robustness tentpole:
fault-isolated multi-tenant scheduling + crash-safe job recovery).

Contracts pinned here:

  * POISON-JOB ISOLATION — a persistent per-job failure (injected
    ``poison_job``) finishes exactly that job ``outcome="poisoned"``
    and frees its slot; every survivor's flux is BITWISE identical to
    the fault-free solo reference.
  * TRANSIENT REPLAY — a transient-classified quantum failure replays
    bitwise from the job's pre-quantum snapshot under the bounded
    retry budget (``pumi_job_retries_total{cause}``); an exhausted
    budget (``job_retries=0``) poisons instead of looping.
  * WATCHDOG CLASSIFICATION — a wedged quantum dispatch
    (``hang_at_move`` + ``quantum_deadline_s``) surfaces as a
    ``DispatchTimeoutError``, classifies transient (the chip answers
    its probe), and replays bitwise — one stuck dispatch cannot stall
    the round-robin loop.
  * CRASH-SAFE JOURNAL — the JOBS.json write-ahead log round-trips
    the whole job table: ``TallyScheduler.recover`` re-queues
    interrupted jobs from their quantum-boundary checkpoints and the
    drained fleet is bitwise vs solo references; a FRESH SUBPROCESS
    recovery over a warm bank compiles NO program family (compile-log
    + bank-counter pinned) and completed jobs keep their persisted
    flux.
  * ADMISSION CONTROL — ``max_queued`` backpressure finishes
    over-limit submissions ``outcome="rejected"`` (named, counted,
    no queue growth, no dispatch).
  * BANK CORRUPTION TOLERANCE — a byte-flipped PROGRAM.bin or a torn
    META.json (driven by ``FaultInjector.corrupt_file`` /
    ``maybe_tear``) degrades to recompile-and-rewrite under
    ``pumi_aot_rewrites_total{cause="corrupt"}``, never crashes a
    dispatch, and the rewritten entry loads clean.

Compile budget: the fast core (-m 'not slow') keeps the grammar /
journal-serialization / admission tests (no XLA compiles); everything
that dispatches real programs or launches subprocesses is marked slow
and runs in the dedicated CI serving-chaos step.
"""
from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import subprocess
import sys

import numpy as np
import pytest

from pumiumtally_tpu import PumiTally, TallyConfig, build_box
from pumiumtally_tpu.ops.source import SourceParams
from pumiumtally_tpu.resilience.faultinject import (
    ChaosInjector,
    ChaosPlan,
    FaultInjector,
    FaultPlan,
    parse_faults,
)
from pumiumtally_tpu.serving import (
    JobRequest,
    ProgramBank,
    TallyScheduler,
    run_saturation,
    synthetic_requests,
)
from pumiumtally_tpu.serving.journal import (
    check_job_id,
    request_from_json,
    request_to_json,
)
from pumiumtally_tpu.tuning.shapes import bucket

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_env(monkeypatch):
    """The serving resilience contracts drive faults/knobs explicitly
    — scrub any CI sweep's env overrides (incl. PUMI_TPU_FAULTS: the
    scheduler's default injector reads it)."""
    for var in (
        "PUMI_TPU_MEGASTEP", "PUMI_TPU_KERNEL", "PUMI_TPU_IO_PIPELINE",
        "PUMI_TPU_TUNING", "PUMI_TPU_AOT_FAULT", "PUMI_TPU_PROM_PORT",
        "PUMI_TPU_FAULTS",
    ):
        monkeypatch.delenv(var, raising=False)


@pytest.fixture(scope="module")
def mesh():
    return build_box(1.0, 1.0, 1.0, 2, 2, 2)


def _cfg(**kw):
    return TallyConfig(tolerance=1e-6, **kw)


def _solo_reference(mesh, request, quantum, cfg):
    """The uninterrupted jit-path run of one scheduler job, padded to
    the same shape bucket with the same chunking (megastep=quantum) —
    what fault-isolated/replayed/recovered execution must match
    bitwise (same helper as tests/test_serving.py)."""
    origins = np.asarray(request.origins, np.float64).reshape(-1, 3)
    n = origins.shape[0]
    N = bucket(n)
    pad = np.broadcast_to(origins[0], (N - n, 3))
    origins_p = np.concatenate([origins, pad], axis=0)
    t = PumiTally(
        mesh, N, dataclasses.replace(cfg, megastep=quantum)
    )
    t.initialize_particle_location(origins_p.reshape(-1).copy())
    t.run_source_moves(
        request.n_moves, request.source,
        weights=np.concatenate([np.ones(n), np.zeros(N - n)]),
        groups=np.zeros(N, np.int32),
        alive=np.concatenate([np.ones(n, bool), np.zeros(N - n, bool)]),
    )
    return t.raw_flux.copy()


# --------------------------------------------------------------------- #
# Fast core: grammar, journal serialization, admission control
# --------------------------------------------------------------------- #
def test_fault_grammar_serving_clauses():
    plan = parse_faults(
        "poison_job:1,transient_quantum:2,kill_server_at_quantum:7"
    )
    assert plan.poison_job == 1
    assert plan.transient_quantum == 2
    assert plan.kill_server_at_quantum == 7
    assert plan.any()
    with pytest.raises(ValueError, match="kill_server_at_quantum"):
        parse_faults("kill_server_at_quantum:0")
    with pytest.raises(ValueError, match="unknown fault"):
        parse_faults("poison_jb:1")
    # The chaos scheduler composes the serving faults with the
    # per-move ones through the inherited FaultPlan hooks.
    inj = ChaosInjector(ChaosPlan(
        poison_job=3, transient_quantum=0, kill_server_at_quantum=5,
    ))
    assert inj.plan.poison_job == 3
    assert inj.plan.transient_quantum == 0
    assert inj.plan.kill_server_at_quantum == 5
    desc = inj.chaos.describe()
    assert "poison_job@3" in desc and "kill_server@q5" in desc
    # poison fires every time; the transient and the kill fire once.
    for _ in range(2):
        with pytest.raises(Exception, match="poison"):
            inj.maybe_poison_job(3)
    with pytest.raises(Exception, match="transient"):
        inj.maybe_transient_quantum(0)
    inj.maybe_transient_quantum(0)  # fired once — silent now
    with pytest.raises(Exception, match="server kill"):
        inj.maybe_kill_server(5)
    inj.maybe_kill_server(5)


def test_journal_request_roundtrip_bitwise():
    """Float64 request payloads survive the JSON journal bitwise
    (repr round-trip), incl. awkward values; SourceParams reconstructs
    with identical tables and seed."""
    rng = np.random.default_rng(5)
    origins = rng.uniform(0.0, 1.0, (7, 3))
    origins[0, 0] = 1.0 / 3.0
    origins[1, 1] = np.nextafter(0.5, 1.0)
    req = JobRequest(
        origins=origins,
        n_moves=9,
        source=SourceParams(
            sigma_t={0: 1.25, 3: 0.7}, absorption={0: 0.31},
            default_sigma_t=0.9, survival_weight=0.05, seed=42,
        ),
        weights=rng.uniform(0.5, 2.0, 7),
        groups=np.array([0, 1, 0, 1, 0, 1, 0], np.int32),
        job_id="rt-0",
    )
    back = request_from_json(
        json.loads(json.dumps(request_to_json(req)))
    )
    assert back.origins.tobytes() == np.asarray(
        origins, np.float64
    ).tobytes()
    assert back.weights.tobytes() == np.asarray(
        req.weights, np.float64
    ).tobytes()
    assert back.groups.tobytes() == req.groups.tobytes()
    assert back.n_moves == 9 and back.job_id == "rt-0"
    assert back.source.seed == 42
    cid = np.arange(4)
    for a, b in zip(back.source.tables(cid), req.source.tables(cid)):
        assert a.tobytes() == b.tobytes()
    # Custom source objects cannot be reconstructed by a fresh
    # recovery process — refused up front, not at recovery time.
    with pytest.raises(TypeError, match="SourceParams"):
        request_to_json(JobRequest(
            origins=origins, n_moves=1, source=object(),
        ))
    # Job ids become journal filenames.
    with pytest.raises(ValueError, match="journal-safe"):
        check_job_id("../evil")


def test_admission_rejection_at_max_queued(mesh, tmp_path):
    """Backpressure is a named terminal outcome, not queue growth —
    and it needs no dispatch (no compiles in this test)."""
    sched = TallyScheduler(
        mesh, _cfg(), max_resident=1, max_queued=2,
        journal_dir=str(tmp_path / "j"), handle_signals=False,
    )
    ids = [
        sched.submit(JobRequest(
            origins=np.full((4, 3), 0.5), n_moves=2, job_id=f"q{i}",
        ))
        for i in range(4)
    ]
    states = [sched.job(i).outcome for i in ids]
    assert states == [None, None, "rejected", "rejected"]
    assert sched.queue_depth == 2
    assert sched.stats()["outcomes"] == {"rejected": 2}
    with pytest.raises(RuntimeError, match="rejected"):
        sched.result("q2")
    text = sched.registry.render_prometheus()
    assert 'pumi_jobs_total{outcome="rejected"} 2' in text
    assert "pumi_job_queue_seconds" in text
    # The rejections are journaled terminal — a recovery does not
    # resurrect them.
    doc = sched.journal.load()
    assert doc["jobs"]["q2"]["state"] == "done"
    assert doc["jobs"]["q2"]["outcome"] == "rejected"
    kinds = [r["kind"] for r in sched.recorder.records()]
    assert kinds.count("job_rejected") == 2
    sched.close()
    with pytest.raises(ValueError, match="max_queued"):
        TallyScheduler(mesh, _cfg(), max_queued=0)


def test_scheduler_new_knob_validation(mesh, tmp_path):
    # preempt_after accepts a journal_dir in place of checkpoint_dir.
    sched = TallyScheduler(
        mesh, _cfg(), preempt_after=1,
        journal_dir=str(tmp_path / "j"), handle_signals=False,
    )
    assert sched.checkpoint_dir is None and sched.journal is not None
    sched.close()
    with pytest.raises(ValueError, match="checkpoint_dir or journal"):
        TallyScheduler(mesh, _cfg(), preempt_after=1)
    # quantum_deadline_s arms the facade watchdog via the job config.
    sched = TallyScheduler(mesh, _cfg(), quantum_deadline_s=5.0)
    assert sched.config.move_deadline_s == 5.0
    sched.close()


# --------------------------------------------------------------------- #
# Fault isolation (slow: real dispatches)
# --------------------------------------------------------------------- #
@pytest.mark.slow
def test_poison_job_isolation_bitwise(mesh):
    """One poison job is finished ``poisoned`` with its slot freed;
    every survivor is bitwise the fault-free solo run."""
    cfg = _cfg()
    reqs = synthetic_requests(
        mesh, 3, class_sizes=(40, 100), n_moves=4, seed=3
    )
    out = run_saturation(
        mesh, cfg, n_jobs=3, class_sizes=(40, 100), n_moves=4, seed=3,
        max_resident=2, quantum_moves=2,
        faults=FaultInjector(FaultPlan(poison_job=1)),
    )
    rows = {r["job"]: r for r in out["per_job"]}
    assert rows["sat-0001"]["outcome"] == "poisoned"
    assert "InjectedPoisonFault" in rows["sat-0001"]["error"]
    assert "sat-0001" not in out["results"]
    assert out["scheduler"]["outcomes"] == {
        "poisoned": 1, "completed": 2,
    }
    for req in (reqs[0], reqs[2]):
        ref = _solo_reference(mesh, req, 2, cfg)
        assert out["results"][req.job_id].tobytes() == ref.tobytes()


@pytest.mark.slow
def test_transient_quantum_bitwise_replay(mesh):
    """A transient-classified quantum failure replays bitwise from the
    job's snapshot; the retry is counted by cause."""
    cfg = _cfg()
    req = synthetic_requests(
        mesh, 1, class_sizes=(40,), n_moves=4, seed=3
    )[0]
    out = run_saturation(
        mesh, cfg, n_jobs=1, class_sizes=(40,), n_moves=4, seed=3,
        max_resident=1, quantum_moves=2,
        faults=FaultInjector(FaultPlan(transient_quantum=0)),
    )
    row = out["per_job"][0]
    assert row["outcome"] == "completed" and row["retries"] == 1
    assert row["recovery_seconds"] > 0
    ref = _solo_reference(mesh, req, 2, cfg)
    assert out["results"][req.job_id].tobytes() == ref.tobytes()


@pytest.mark.slow
def test_retry_budget_exhaustion_poisons(mesh):
    """job_retries=0: even a transient verdict cannot replay — the
    job is poisoned (named), the server stays healthy."""
    out = run_saturation(
        mesh, _cfg(), n_jobs=2, class_sizes=(40,), n_moves=4, seed=3,
        max_resident=1, quantum_moves=2, job_retries=0,
        faults=FaultInjector(FaultPlan(transient_quantum=0)),
    )
    rows = {r["job"]: r for r in out["per_job"]}
    assert rows["sat-0000"]["outcome"] == "poisoned"
    assert "InjectedTransientFault" in rows["sat-0000"]["error"]
    assert rows["sat-0001"]["outcome"] == "completed"


@pytest.mark.slow
def test_watchdog_timeout_classified_and_replayed(mesh, monkeypatch):
    """A wedged quantum dispatch hits the PR 4 watchdog deadline, the
    timeout classifies transient (the chip still answers its probe),
    and the quantum replays bitwise — counted under cause="timeout"."""
    cfg = _cfg()
    req = synthetic_requests(
        mesh, 1, class_sizes=(40,), n_moves=4, seed=3
    )[0]
    # The facade's own injector wedges move 3 — the SECOND quantum,
    # past the first-dispatch compile amnesty, so the armed deadline
    # fires.
    monkeypatch.setenv(
        "PUMI_TPU_FAULTS", "hang_at_move:3,hang_seconds:1.5"
    )
    out = run_saturation(
        mesh, cfg, n_jobs=1, class_sizes=(40,), n_moves=4, seed=3,
        max_resident=1, quantum_moves=2, quantum_deadline_s=0.3,
        faults=FaultInjector(FaultPlan()),  # scheduler faults: none
    )
    monkeypatch.delenv("PUMI_TPU_FAULTS")
    row = out["per_job"][0]
    assert row["outcome"] == "completed" and row["retries"] >= 1
    ref = _solo_reference(mesh, req, 2, cfg)
    assert out["results"][req.job_id].tobytes() == ref.tobytes()
    retried = out["scheduler"]["retries"]
    assert retried >= 1


# --------------------------------------------------------------------- #
# Crash-safe journal + recovery (slow)
# --------------------------------------------------------------------- #
@pytest.mark.slow
def test_journal_roundtrip_recovery_in_process(mesh, tmp_path):
    """An abandoned scheduler's journal recovers in-process: completed
    jobs keep their persisted flux, interrupted jobs resume from their
    quantum-boundary checkpoints, and the drained fleet is bitwise vs
    solo references."""
    cfg = _cfg()
    jdir = str(tmp_path / "journal")
    reqs = synthetic_requests(
        mesh, 3, class_sizes=(40,), n_moves=6, seed=11
    )
    sched = TallyScheduler(
        mesh, cfg, max_resident=1, quantum_moves=2,
        journal_dir=jdir, handle_signals=False,
    )
    for r in reqs:
        sched.submit(r)
    # Enough rounds to finish the first job and leave the second
    # mid-flight with a journaled checkpoint; then 'crash' (no close).
    for _ in range(4):
        sched.step()
    assert sched.job("sat-0000").outcome == "completed"
    mid = sched.job("sat-0001")
    assert 0 < mid.moves_done < 6
    doc = sched.journal.load()
    assert doc["jobs"]["sat-0000"]["state"] == "done"
    assert doc["jobs"]["sat-0001"]["checkpoint"] is not None
    del sched

    rec = TallyScheduler.recover(
        jdir, mesh, cfg, max_resident=1, quantum_moves=2,
        handle_signals=False,
    )
    # The completed job came back terminal WITH its flux (no re-run).
    done = rec.job("sat-0000")
    assert done.outcome == "completed" and done.result is not None
    # The mid-flight job resumes from its checkpoint, not move 0.
    resumed = rec.job("sat-0001")
    assert resumed.checkpoint is not None and resumed.moves_done > 0
    assert rec.stats()["recovered"] == 2  # sat-0001 + sat-0002
    rec.run()
    rec.close()
    for req in reqs:
        ref = _solo_reference(mesh, req, 2, cfg)
        assert rec.result(req.job_id).tobytes() == ref.tobytes(), req.job_id
    kinds = [r["kind"] for r in rec.recorder.records()]
    assert "journal_recovery" in kinds and "journal_recovered" in kinds


_RECOVER_SCRIPT = """
import os, sys, json, hashlib, logging
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    )
msgs = []
class _H(logging.Handler):
    def emit(self, rec):
        msgs.append(rec.getMessage())
logging.getLogger().addHandler(_H())
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)
jax.config.update("jax_log_compiles", True)
sys.path.insert(0, {root!r})
import numpy as np
from pumiumtally_tpu import TallyConfig, build_box
from pumiumtally_tpu.serving import run_saturation
mesh = build_box(1.0, 1.0, 1.0, 2, 2, 2)
out = run_saturation(
    mesh, TallyConfig(tolerance=1e-6), bank={bank!r}, n_jobs=3,
    class_sizes=(40,), n_moves=4, seed=5, max_resident=1,
    quantum_moves=2, journal_dir={journal!r}, resume=True,
)
hashes = {{
    k: hashlib.sha256(v.tobytes()).hexdigest()
    for k, v in sorted(out["results"].items())
}}
family_compiles = [
    m for m in msgs
    if "Finished XLA compilation" in m
    and ("trace_packed" in m or "megastep" in m)
]
outcomes = {{}}
for row in out["per_job"]:
    outcomes[row["outcome"]] = outcomes.get(row["outcome"], 0) + 1
print(json.dumps({{
    "stats": out["scheduler"]["aot"],
    "recovered": out["scheduler"]["recovered"],
    "hashes": hashes,
    "family_compiles": family_compiles,
    "outcomes": outcomes,
}}))
"""


@pytest.mark.slow
def test_journal_recovery_subprocess_zero_compiles(mesh, tmp_path):
    """The acceptance pin: a FRESH process recovers an interrupted
    journaled fleet over a warm bank with zero bank misses, no XLA
    compile of either program family (compile log), and results
    bitwise-identical to the uninterrupted reference."""
    bank_dir = str(tmp_path / "bank")
    jdir = str(tmp_path / "journal")
    cfg = _cfg()
    # Uninterrupted reference over a cold bank (also populates it).
    ref = run_saturation(
        mesh, cfg, bank=ProgramBank(bank_dir), n_jobs=3,
        class_sizes=(40,), n_moves=4, seed=5, max_resident=1,
        quantum_moves=2,
    )
    want = {
        k: hashlib.sha256(v.tobytes()).hexdigest()
        for k, v in sorted(ref["results"].items())
    }
    # Interrupted journaled run: a few rounds, then 'crash'.
    sched = TallyScheduler(
        mesh, cfg, bank=bank_dir, max_resident=1, quantum_moves=2,
        journal_dir=jdir, handle_signals=False,
    )
    for r in synthetic_requests(
        mesh, 3, class_sizes=(40,), n_moves=4, seed=5
    ):
        sched.submit(r)
    for _ in range(3):
        sched.step()
    assert any(j.moves_done > 0 and j.outcome is None
               for j in sched.jobs())
    del sched

    env = {
        k: v for k, v in os.environ.items()
        if not k.startswith("PUMI_TPU_")
        and k not in ("JAX_COMPILATION_CACHE_DIR",)
    }
    proc = subprocess.run(
        [sys.executable, "-c",
         _RECOVER_SCRIPT.format(root=ROOT, bank=bank_dir, journal=jdir)],
        capture_output=True, text=True, timeout=600, env=env, cwd=ROOT,
    )
    assert proc.returncode == 0, proc.stderr
    got = json.loads(proc.stdout.strip().splitlines()[-1])
    assert got["recovered"] >= 1
    assert got["stats"]["misses"] == 0, got["stats"]
    assert got["stats"]["compile_seconds"] == 0.0, got["stats"]
    assert got["family_compiles"] == [], got["family_compiles"]
    assert got["outcomes"] == {"completed": 3}
    assert got["hashes"] == want


# --------------------------------------------------------------------- #
# Bank corruption tolerance (slow)
# --------------------------------------------------------------------- #
@pytest.mark.slow
def test_torn_bank_entry_degrades_to_rewrite(mesh, tmp_path):
    """A byte-flipped PROGRAM.bin and a torn META.json (the
    FaultInjector's own corruption drivers) each degrade to a
    recompile-and-rewrite under cause="corrupt" — never a crashed
    dispatch — and the rewritten entries load clean."""
    cfg = _cfg(megastep=2)

    def run_via(bank):
        t = PumiTally(mesh, 64, cfg, program_bank=bank)
        cents = np.asarray(mesh.centroids(), np.float64)
        origins = cents[np.arange(64) % mesh.ntet].reshape(-1).copy()
        t.initialize_particle_location(origins)
        t.run_source_moves(
            4, SourceParams(seed=7),
            weights=np.ones(64), groups=np.zeros(64, np.int32),
            alive=np.ones(64, bool),
        )
        out = np.asarray(t.flux).copy()
        t.close()
        return out

    cold = ProgramBank(str(tmp_path))
    f_ref = run_via(cold)
    entries = cold.entries_on_disk()
    assert len(entries) == 2
    # Corrupt one entry's program bytes, tear the other's META —
    # through the injector's file-corruption drivers.
    prog = os.path.join(
        cold.section_dir, entries[0], "PROGRAM.bin"
    )
    meta = os.path.join(cold.section_dir, entries[1], "META.json")
    assert FaultInjector(
        FaultPlan(corrupt_ckpt=True)
    ).corrupt_file(prog)
    assert FaultInjector(FaultPlan(torn_shard=1)).maybe_tear(meta)
    hurt = ProgramBank(str(tmp_path))
    f_hurt = run_via(hurt)
    assert f_hurt.tobytes() == f_ref.tobytes()
    assert hurt.rewrites == 2 and hurt.hits == 0
    causes = {
        s["labels"]["cause"]
        for s in hurt._rewrites.snapshot()["series"]
    }
    assert causes == {"corrupt"}
    # The rewritten entries are whole again: pure hits, no findings.
    clean = ProgramBank(str(tmp_path))
    f_clean = run_via(clean)
    assert f_clean.tobytes() == f_ref.tobytes()
    assert clean.hits == 2 and clean.rewrites == 0
    assert clean.findings == []
