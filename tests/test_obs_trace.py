"""Per-job distributed tracing contracts (pumiumtally_tpu/obs/trace.py
+ the serving-stack integration, the observability tentpole).

Contracts pinned here:

  * SPAN MODEL — span/event records carry the schema stamp, ids,
    parentage and timing; pre-allocated span ids let children nest
    under a parent emitted at close; ``NO_PARENT`` keeps the terminal
    root span from inheriting the ambient binding; disabled tracers
    are no-ops (records stay empty, context managers still run).
  * LIFECYCLE — a served job's trace reads submit → queued → admit →
    quantum... → terminal ``job`` root span, every parent resolvable,
    one trace_id, with per-quantum device-time attribution summing
    into the job's ``device_seconds`` and the
    ``pumi_job_device_seconds`` / SLO histogram metrics.
  * CRASH CONTINUITY — the journal persists ``trace_id`` (schema 2),
    so a subprocess ``--resume`` recovery CONTINUES the trace: spans
    from both process lifetimes stitch into one causally-ordered
    timeline through the deterministic root id and an explicit
    ``recovered`` link (teleview --job --check is the gate).
  * BLACK BOX — poisoning a job dumps the span ring atomically; the
    dump is readable and contains the poisoned job's final spans.
  * ZERO COST TO PHYSICS — served fluxes are bitwise identical with
    tracing on vs ``PUMI_TPU_TRACE=off``.
  * ENDPOINTS — /jobs and /trace render from a live scheduler
    exporter; /buildz names the build; 404 bodies name the valid
    endpoints; teleview's checker flags each causal defect class.

Compile budget: the fast core (-m 'not slow') drives only the tracer,
the exporter, the rejection path (no dispatch) and teleview's pure
functions; everything dispatching real programs or launching
subprocesses is marked slow.
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
import urllib.error
import urllib.request

import numpy as np
import pytest

from pumiumtally_tpu import TallyConfig, build_box
from pumiumtally_tpu.obs import (
    FLIGHT_SCHEMA,
    NO_PARENT,
    SpanTracer,
    TRACE_SCHEMA,
    trace_enabled,
)
from pumiumtally_tpu.obs.exporter import MetricsExporter, build_info
from pumiumtally_tpu.obs.registry import MetricsRegistry
from pumiumtally_tpu.resilience.faultinject import ChaosInjector, ChaosPlan
from pumiumtally_tpu.serving import (
    JobRequest,
    TallyScheduler,
    run_saturation,
    synthetic_requests,
)
from pumiumtally_tpu.serving.journal import (
    JOURNAL_SCHEMA,
    JOURNAL_SCHEMAS_READABLE,
)

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(ROOT, "scripts"))

from teleview import (  # noqa: E402
    check_job_trace,
    job_trace,
    load_trace_records,
)


@pytest.fixture(autouse=True)
def _clean_env(monkeypatch):
    """Tracing contracts drive the knobs explicitly — scrub any CI
    sweep's env overrides (incl. PUMI_TPU_TRACE: the tracer reads it
    at construction)."""
    for var in (
        "PUMI_TPU_MEGASTEP", "PUMI_TPU_KERNEL", "PUMI_TPU_IO_PIPELINE",
        "PUMI_TPU_TUNING", "PUMI_TPU_AOT_FAULT", "PUMI_TPU_PROM_PORT",
        "PUMI_TPU_FAULTS", "PUMI_TPU_TRACE", "PUMI_TPU_METRICS",
    ):
        monkeypatch.delenv(var, raising=False)


@pytest.fixture(scope="module")
def mesh():
    return build_box(1.0, 1.0, 1.0, 2, 2, 2)


def _cfg(**kw):
    return TallyConfig(tolerance=1e-6, **kw)


# --------------------------------------------------------------------- #
# Fast core: the span model
# --------------------------------------------------------------------- #
def test_span_nesting_and_ordering():
    tr = SpanTracer(enabled=True)
    tid = SpanTracer.new_trace()
    root = SpanTracer.root_id(tid)
    assert root == f"{tid}/root" == SpanTracer.root_id(tid)
    tr.event("submit", trace_id=tid, parent=root, job_id="j1", n=4)
    qid = tr.next_id()
    with tr.bind(tid, "j1", qid):
        assert tr.current == (tid, "j1", qid)
        # A child span emitted while the parent is still open inherits
        # the ambient parent (the bank/coordinator pattern).
        with tr.span("aot_resolve", key="k") as sp:
            sp["outcome"] = "hit"
    tr.span_record("quantum", 0.25, trace_id=tid, parent=root,
                   job_id="j1", span_id=qid, k=4)
    tr.span_record("job", 1.0, trace_id=tid, parent=NO_PARENT,
                   job_id="j1", span_id=root, outcome="completed")
    recs = tr.records()
    assert [r["name"] for r in recs] == [
        "submit", "aot_resolve", "quantum", "job",
    ]
    assert all(r["schema"] == TRACE_SCHEMA for r in recs)
    assert all(r["trace_id"] == tid for r in recs)
    seqs = [r["seq"] for r in recs]
    assert seqs == sorted(seqs)
    by_name = {r["name"]: r for r in recs}
    # The child nests under the pre-allocated quantum id, the quantum
    # under the root, and the root span itself has NO parent (the
    # NO_PARENT sentinel beats any ambient binding).
    assert by_name["aot_resolve"]["parent_id"] == qid
    assert by_name["aot_resolve"]["outcome"] == "hit"
    assert by_name["quantum"]["span_id"] == qid
    assert by_name["quantum"]["parent_id"] == root
    assert by_name["job"]["span_id"] == root
    assert by_name["job"]["parent_id"] is None
    # Outside the bind the ambient context is gone.
    assert tr.current == (None, None, None)
    # And the whole thing passes the causal checker.
    assert check_job_trace(job_trace(recs, "j1"), "j1") == []


def test_span_emitted_on_exception():
    tr = SpanTracer(enabled=True)
    with pytest.raises(RuntimeError, match="boom"):
        with tr.span("classify") as sp:
            sp["verdict"] = "pending"
            raise RuntimeError("boom")
    (rec,) = tr.records()
    assert rec["name"] == "classify"
    assert rec["error"].startswith("RuntimeError: boom")


def test_disabled_tracer_is_noop(monkeypatch):
    assert trace_enabled()
    monkeypatch.setenv("PUMI_TPU_TRACE", "off")
    assert not trace_enabled()
    tr = SpanTracer()  # picks the env up at construction
    assert tr.event("submit") is None
    with tr.span("quantum") as sp:
        sp["k"] = 1  # the context manager still runs the body
    assert tr.span_record("job", 1.0) is None
    assert len(tr) == 0 and tr.records() == []


def test_ring_bound_and_blackbox_dump(tmp_path):
    tr = SpanTracer(capacity=8, enabled=True)
    for i in range(20):
        tr.event("tick", job_id="j", i=i)
    assert len(tr) == 8
    assert [r["i"] for r in tr.records()] == list(range(12, 20))
    path = str(tmp_path / "j.blackbox.json")
    doc = tr.dump(path, reason="poisoned:persistent", meta={"job_id": "j"})
    with open(path) as fh:
        on_disk = json.load(fh)
    assert on_disk == json.loads(json.dumps(doc))
    assert on_disk["kind"] == "blackbox"
    assert on_disk["schema"] == TRACE_SCHEMA
    assert on_disk["reason"] == "poisoned:persistent"
    assert on_disk["meta"] == {"job_id": "j"}
    assert [r["i"] for r in on_disk["records"]] == list(range(12, 20))
    with pytest.raises(ValueError, match="capacity"):
        SpanTracer(capacity=0)


def test_trace_jsonl_sink_streams_records(tmp_path):
    sink = str(tmp_path / "TRACE.jsonl")
    tr = SpanTracer(sink=sink, enabled=True)
    tid = SpanTracer.new_trace()
    tr.event("submit", trace_id=tid, job_id="j1")
    tr.span_record("job", 0.5, trace_id=tid, job_id="j1",
                   span_id=SpanTracer.root_id(tid), parent=NO_PARENT)
    lines = [
        json.loads(x)
        for x in open(sink).read().splitlines() if x.strip()
    ]
    assert [r["name"] for r in lines] == ["submit", "job"]
    # The loader reads the stream back and dedups against a dump of
    # the same ring.
    tr.dump(str(tmp_path / "x.blackbox.json"), reason="shutdown")
    recs = load_trace_records(str(tmp_path))
    assert len(recs) == 2


def test_chrome_trace_export_is_lossless():
    tr = SpanTracer(enabled=True)
    tid = SpanTracer.new_trace()
    tr.event("submit", trace_id=tid, job_id="j1")
    tr.span_record("quantum", 0.5, trace_id=tid, job_id="j1", k=4)
    doc = tr.chrome()
    events = [e for e in doc["traceEvents"] if e.get("ph") in ("X", "i")]
    assert len(events) == 2
    phases = {e["args"]["name"]: e["ph"] for e in events}
    assert phases == {"submit": "i", "quantum": "X"}
    # The raw record rides in args — teleview reconstructs from it.
    args = [e["args"] for e in events]
    assert all(a["trace_id"] == tid and "span_id" in a for a in args)


# --------------------------------------------------------------------- #
# Fast core: teleview causal checker
# --------------------------------------------------------------------- #
def _mk(name, *, kind="span", tid="t1", sid, parent=None, pid=1, ts=1.0,
        seq=0, **attrs):
    return dict(
        schema=TRACE_SCHEMA, kind=kind, name=name, trace_id=tid,
        span_id=sid, parent_id=parent, job_id="jX", pid=pid, ts=ts,
        seconds=0.0, seq=seq, **attrs,
    )


def test_teleview_check_flags_each_defect_class():
    root = "t1/root"
    good = [
        _mk("submit", kind="event", sid="a", parent=root, seq=0),
        _mk("quantum", sid="b", parent=root, seq=1),
        _mk("job", sid=root, seq=2),
    ]
    assert check_job_trace(job_trace(good, "jX"), "jX") == []
    assert check_job_trace([], "jX") == ["no span records for job jX"]
    # Two trace ids in one job's records.
    forked = good + [_mk("retry", kind="event", tid="t2", sid="z", seq=3)]
    assert any(
        "one trace_id" in p
        for p in check_job_trace(job_trace(forked, "jX"), "jX")
    )
    # Missing submit / missing terminal root span.
    assert any(
        "no submit" in p
        for p in check_job_trace(job_trace(good[1:], "jX"), "jX")
    )
    assert any(
        "root span" in p
        for p in check_job_trace(job_trace(good[:2], "jX"), "jX")
    )
    # A dangling parent id.
    torn = good + [_mk("probe", sid="c", parent="gone", seq=4)]
    assert any(
        "unresolvable" in p
        for p in check_job_trace(job_trace(torn, "jX"), "jX")
    )
    # Two process lifetimes without an explicit recovered link...
    split = good + [_mk("quantum", sid="d", parent=root, pid=2, seq=5)]
    assert any(
        "recovered" in p
        for p in check_job_trace(job_trace(split, "jX"), "jX")
    )
    # ...and with one: clean.
    healed = split + [
        _mk("recovered", kind="event", sid="e", parent=root, pid=2, seq=6)
    ]
    assert check_job_trace(job_trace(healed, "jX"), "jX") == []
    # Unknown fields from a newer schema ride along untouched.
    future = [dict(r, schema=99, new_field="x") for r in good]
    assert check_job_trace(job_trace(future, "jX"), "jX") == []


# --------------------------------------------------------------------- #
# Fast core: exporter endpoints
# --------------------------------------------------------------------- #
def _get(url):
    with urllib.request.urlopen(url, timeout=5) as resp:
        return resp.status, resp.read().decode()


def test_exporter_buildz_and_extra_endpoints():
    reg = MetricsRegistry()
    reg.counter("demo_total", "demo").inc()
    exp = MetricsExporter(
        reg, port=0, endpoints={"/jobs": lambda: {"jobs": [1, 2]}},
    )
    base = exp.url.replace("/metrics", "")
    try:
        status, body = _get(base + "/buildz")
        build = json.loads(body)
        assert status == 200
        for key in ("package", "version", "backend", "x64",
                    "n_devices", "pid"):
            assert key in build, key
        assert build["package"] == "pumiumtally_tpu"
        status, body = _get(base + "/jobs")
        assert status == 200 and json.loads(body) == {"jobs": [1, 2]}
        # The 404 body names every valid endpoint.
        with pytest.raises(urllib.error.HTTPError) as ei:
            _get(base + "/nope")
        err_body = ei.value.read().decode()
        assert ei.value.code == 404
        for ep in ("/metrics", "/healthz", "/buildz", "/jobs"):
            assert ep in err_body, err_body
    finally:
        exp.stop()
    # build_info never raises, whatever the backend state.
    assert isinstance(build_info(), dict)


def test_exporter_endpoint_exception_is_500_not_crash():
    reg = MetricsRegistry()

    def broken():
        raise RuntimeError("collector died")

    exp = MetricsExporter(reg, port=0, endpoints={"/jobs": broken})
    try:
        with pytest.raises(urllib.error.HTTPError) as ei:
            _get(exp.url.replace("/metrics", "/jobs"))
        assert ei.value.code == 500
        # The exporter thread survived — /healthz still answers.
        status, body = _get(exp.url.replace("/metrics", "/healthz"))
        assert status == 200 and body == "ok\n"
    finally:
        exp.stop()


# --------------------------------------------------------------------- #
# Fast core: scheduler integration without dispatch (rejection path)
# --------------------------------------------------------------------- #
def test_rejection_path_traced_and_flight_schema(mesh, tmp_path,
                                                 monkeypatch):
    monkeypatch.setenv("PUMI_TPU_PROM_PORT", "0")
    sched = TallyScheduler(
        mesh, _cfg(), max_resident=1, max_queued=1,
        journal_dir=str(tmp_path / "j"), handle_signals=False,
    )
    try:
        for i in range(3):
            sched.submit(JobRequest(
                origins=np.full((4, 3), 0.5), n_moves=2, job_id=f"q{i}",
            ))
        # Every serving-path flight record carries the schema stamp and
        # a job id (satellite: ride-along attribution).
        recs = sched.recorder.records()
        assert recs and all(r["schema"] == FLIGHT_SCHEMA for r in recs)
        assert all("job_id" in r for r in recs)
        # The rejected job got a full (if short) trace: submit +
        # terminal root span with outcome=rejected.
        trace = job_trace(sched.tracer.records(), "q2")
        assert check_job_trace(trace, "q2") == []
        job_span = [r for r in trace if r["name"] == "job"][0]
        assert job_span["outcome"] == "rejected"
        # trace_id is journaled (schema 2) for crash continuity.
        assert JOURNAL_SCHEMA == 2 and 1 in JOURNAL_SCHEMAS_READABLE
        doc = sched.journal.load()
        assert doc["schema"] == JOURNAL_SCHEMA
        assert doc["jobs"]["q2"]["trace_id"] == sched.job("q2").trace_id
        # /jobs and /trace render live from the exporter.
        base = sched._exporter.url.replace("/metrics", "")
        status, body = _get(base + "/jobs")
        rows = json.loads(body)
        assert status == 200 and rows["schema"] == FLIGHT_SCHEMA
        byid = {r["id"]: r for r in rows["jobs"]}
        assert byid["q2"]["outcome"] == "rejected"
        assert byid["q2"]["trace_id"] == sched.job("q2").trace_id
        status, body = _get(base + "/trace")
        chrome = json.loads(body)
        assert status == 200 and any(
            e.get("args", {}).get("job_id") == "q2"
            for e in chrome["traceEvents"]
        )
        # The SLO histogram saw the terminal transitions.
        text = sched.registry.render_prometheus()
        assert "pumi_job_e2e_seconds" in text
    finally:
        sched.close()
    # close() leaves the shutdown black box beside the journal.
    bb = os.path.join(str(tmp_path / "j"), "shutdown.blackbox.json")
    with open(bb) as fh:
        assert json.load(fh)["kind"] == "blackbox"


# --------------------------------------------------------------------- #
# Slow: full lifecycle, poison black box, bitwise parity, recovery
# --------------------------------------------------------------------- #
@pytest.mark.slow
def test_full_lifecycle_trace_and_device_attribution(mesh, tmp_path):
    jdir = str(tmp_path / "j")
    out = run_saturation(
        mesh, _cfg(), n_jobs=2, class_sizes=(40,), n_moves=4,
        max_resident=1, quantum_moves=2, journal_dir=jdir,
    )
    recs = load_trace_records(jdir)
    for row in out["per_job"]:
        jid = row["job"]
        trace = job_trace(recs, jid)
        assert check_job_trace(trace, jid) == [], jid
        names = [r["name"] for r in trace]
        for expected in ("submit", "queued", "admit", "quantum", "job"):
            assert expected in names, (jid, names)
        assert names.index("submit") < names.index("admit") \
            < names.index("quantum") < names.index("job")
        # Device-time attribution: each quantum span carries its
        # blocked-dispatch seconds; they sum into the job row and the
        # terminal span.
        q_dev = sum(
            r["device_seconds"] for r in trace if r["name"] == "quantum"
        )
        assert q_dev > 0
        assert row["device_seconds"] == pytest.approx(q_dev, abs=1e-3)
        job_span = [r for r in trace if r["name"] == "job"][0]
        assert job_span["outcome"] == "completed"
        assert job_span["device_seconds"] == pytest.approx(
            q_dev, abs=1e-3
        )
    sched_stats = out["scheduler"]
    assert sched_stats["device_seconds"] > 0


@pytest.mark.slow
def test_poison_blackbox_contains_final_spans(mesh, tmp_path):
    bdir = str(tmp_path / "bb")
    out = run_saturation(
        mesh, _cfg(), n_jobs=2, class_sizes=(40,), n_moves=4,
        max_resident=1, quantum_moves=2, blackbox_dir=bdir,
        faults=ChaosInjector(ChaosPlan(poison_job=1)), job_retries=1,
    )
    rows = {r["job"]: r for r in out["per_job"]}
    assert rows["sat-0001"]["outcome"] == "poisoned"
    path = os.path.join(bdir, "sat-0001.blackbox.json")
    with open(path) as fh:
        doc = json.load(fh)
    assert doc["kind"] == "blackbox"
    assert doc["reason"].startswith("poisoned:")
    assert doc["meta"]["job_id"] == "sat-0001"
    assert doc["meta"]["trace_id"] == rows["sat-0001"]["trace_id"]
    # The ring holds the poisoned job's final moments: its failing
    # quantum and its terminal span are both in the dump.
    mine = job_trace(doc["records"], "sat-0001")
    names = [r["name"] for r in mine]
    assert "job" in names
    job_span = [r for r in mine if r["name"] == "job"][0]
    assert job_span["outcome"] == "poisoned"
    quantum = [r for r in mine if r["name"] == "quantum"]
    assert quantum and "error" in quantum[-1]


@pytest.mark.slow
def test_bitwise_parity_tracing_on_vs_off(mesh, monkeypatch):
    kw = dict(
        n_jobs=2, class_sizes=(40,), n_moves=4, max_resident=1,
        quantum_moves=2, seed=9,
    )
    on = run_saturation(mesh, _cfg(), **kw)
    monkeypatch.setenv("PUMI_TPU_TRACE", "off")
    off = run_saturation(mesh, _cfg(), **kw)
    assert sorted(on["results"]) == sorted(off["results"])
    for jid in on["results"]:
        assert on["results"][jid].tobytes() == \
            off["results"][jid].tobytes(), jid


@pytest.mark.slow
def test_trace_id_survives_subprocess_recovery(mesh, tmp_path):
    """The crash-continuity pin: interrupt a journaled fleet, recover
    it in a FRESH process, and reconstruct every job's single
    causally-ordered trace — spanning both pids, stitched by the
    persisted trace_id + recovered link — from the journal dir alone."""
    jdir = str(tmp_path / "journal")
    sched = TallyScheduler(
        mesh, _cfg(), max_resident=1, quantum_moves=2,
        journal_dir=jdir, handle_signals=False,
    )
    for r in synthetic_requests(
        mesh, 3, class_sizes=(40,), n_moves=4, seed=5
    ):
        sched.submit(r)
    for _ in range(3):
        sched.step()
    assert any(j.moves_done > 0 and j.outcome is None
               for j in sched.jobs())
    trace_ids = {j.id: j.trace_id for j in sched.jobs()}
    kill_pid = os.getpid()
    del sched

    env = {
        k: v for k, v in os.environ.items()
        if not k.startswith("PUMI_TPU_")
    }
    env["JAX_PLATFORMS"] = "cpu"
    script = (
        "import sys; sys.path.insert(0, {root!r})\n"
        "from pumiumtally_tpu import TallyConfig, build_box\n"
        "from pumiumtally_tpu.serving import run_saturation\n"
        "mesh = build_box(1.0, 1.0, 1.0, 2, 2, 2)\n"
        "out = run_saturation(\n"
        "    mesh, TallyConfig(tolerance=1e-6), n_jobs=3,\n"
        "    class_sizes=(40,), n_moves=4, seed=5, max_resident=1,\n"
        "    quantum_moves=2, journal_dir={journal!r}, resume=True,\n"
        ")\n"
        "assert out['scheduler']['recovered'] >= 1\n"
    ).format(root=ROOT, journal=jdir)
    proc = subprocess.run(
        [sys.executable, "-c", script],
        capture_output=True, text=True, timeout=600, env=env, cwd=ROOT,
    )
    assert proc.returncode == 0, proc.stderr
    recs = load_trace_records(jdir)
    for jid, tid in trace_ids.items():
        trace = job_trace(recs, jid)
        assert check_job_trace(trace, jid) == [], jid
        assert {r["trace_id"] for r in trace} == {tid}, jid
        pids = {r["pid"] for r in trace}
        if len(pids) > 1:
            # A recovered job's trace spans both lifetimes, linked.
            assert kill_pid in pids
            assert "recovered" in [r["name"] for r in trace]
    # At least one job actually crossed the process boundary.
    assert any(
        len({r["pid"] for r in job_trace(recs, jid)}) > 1
        for jid in trace_ids
    )
    # The teleview CLI gate agrees (the chaos campaign's driver).
    cli = subprocess.run(
        [sys.executable, os.path.join(ROOT, "scripts", "teleview.py"),
         jdir, "--job", "sat-0001", "--check"],
        capture_output=True, text=True, timeout=120,
    )
    assert cli.returncode == 0, cli.stdout + cli.stderr
