"""Self-healing fleet contracts (the ISSUE 19 robustness tentpole:
health-probe-driven eviction, brownout detection, and disk-pressure
degradation — no explicit kill signal anywhere).

Contracts pinned here:

  * WEDGED DETECTION — a member that stops answering heartbeat probes
    (``wedge_member`` injection: no exception, no kill, just silence)
    is quarantined after ``heartbeat_misses`` consecutive misses and
    evicted after ``grace_ticks`` more unhealthy ticks; its journaled
    jobs re-place onto survivors and finish BITWISE vs the fault-free
    fleet, with the ``evicted`` trace link and FLEET.json record.
  * FALSE-POSITIVE RESISTANCE — a merely-slow member (brownout) is
    quarantined (no new placements) but NOT evicted inside the grace
    window; when its latency recovers it is restored and its jobs
    finish bitwise in place — zero migrations.
  * DISK-PRESSURE DEGRADATION — ENOSPC-class failures flip the
    journal's sticky ``degraded`` flag instead of crashing
    (``pumi_journal_degraded`` gauge), the supervisor classifies the
    member disk-pressured, and the cooperative drain hands every job
    (including unpersisted in-memory results) to healthy peers with
    zero lost / zero duplicated.
  * EVICTION-RECORD-BEFORE-DRAIN — the FLEET.json ``evicted`` record
    is flushed before any drain work (protolint-checked ordering in
    the supervisor); a crash between record and drain replays the
    drain at ``FleetRouter.recover`` with no orphans or duplicates.
  * GATEWAY BACKPRESSURE — a saturated fleet answers ``POST /submit``
    with 503 + ``Retry-After`` + jittered-backoff guidance BEFORE any
    idempotency key is journaled; per-request socket deadlines are
    validated knobs.
  * FAULT GRAMMAR — ``wedge_member:M`` / ``slow_member:M:F`` /
    ``disk_full_at:N`` parse, validate, and appear in the
    unknown-clause teaching message; teleview's causal checker
    accepts ``evicted`` as a cross-lifetime link.

Compile budget: the fast core (-m 'not slow') covers classification,
hysteresis, grammar, journal degradation, recovery replay, and the
gateway — none of it runs a quantum.  The three end-to-end bitwise
drills (wedged / brownout / disk-pressure) are marked slow and run in
the CI self-healing step beside scripts/chaos_fleet.py.
"""
from __future__ import annotations

import errno
import json
import os
import sys
import urllib.error
import urllib.request

import numpy as np
import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(ROOT, "scripts"))

from teleview import check_job_trace, job_trace  # noqa: E402

from pumiumtally_tpu import TallyConfig, build_box
from pumiumtally_tpu.obs import TRACE_SCHEMA
from pumiumtally_tpu.resilience import ChaosInjector, ChaosPlan
from pumiumtally_tpu.resilience.faultinject import parse_faults
from pumiumtally_tpu.serving import (
    FleetJournal,
    FleetRouter,
    FleetSupervisor,
    TallyGateway,
    synthetic_requests,
)
from pumiumtally_tpu.serving.journal import SchedulerJournal


@pytest.fixture(autouse=True)
def _clean_env(monkeypatch):
    """Supervisor contracts drive faults explicitly — scrub any CI
    sweep's env overrides."""
    for var in (
        "PUMI_TPU_MEGASTEP", "PUMI_TPU_KERNEL", "PUMI_TPU_IO_PIPELINE",
        "PUMI_TPU_TUNING", "PUMI_TPU_AOT_FAULT", "PUMI_TPU_PROM_PORT",
        "PUMI_TPU_FAULTS",
    ):
        monkeypatch.delenv(var, raising=False)


@pytest.fixture(scope="module")
def mesh():
    return build_box(1.0, 1.0, 1.0, 2, 2, 2)


def _cfg(**kw):
    return TallyConfig(tolerance=1e-6, **kw)


def _router(tmp_path, mesh, n_members=3, **kw):
    kw.setdefault("quantum_moves", 2)
    kw.setdefault("max_resident", 2)
    return FleetRouter(
        mesh, _cfg(), fleet_dir=str(tmp_path / "fleet"),
        n_members=n_members, bank=None, **kw,
    )


def _reference_results(tmp_path, mesh, requests, **kw):
    kw.setdefault("quantum_moves", 2)
    ref = FleetRouter(
        mesh, _cfg(), fleet_dir=str(tmp_path / "ref"), n_members=2,
        bank=None, max_resident=2, **kw,
    )
    try:
        for r in requests:
            ref.submit(r, idempotency_key=f"key-{r.job_id}")
        ref.run()
        return {r.job_id: np.asarray(ref.result(r.job_id)).copy()
                for r in requests}
    finally:
        ref.close()


def _health(router, member, state):
    return router.registry.gauge("pumi_member_health").value(
        member=f"m{member}", state=state,
    )


# --------------------------------------------------------------------- #
# Fast core: knobs + classification state machine (no quanta)
# --------------------------------------------------------------------- #
def test_supervisor_knob_validation(tmp_path, mesh):
    router = _router(tmp_path, mesh, n_members=1)
    try:
        with pytest.raises(ValueError, match="slow_factor"):
            FleetSupervisor(router, slow_factor=1.0)
        for bad in ("window", "heartbeat_misses", "grace_ticks",
                    "restore_ticks"):
            with pytest.raises(ValueError, match=bad):
                FleetSupervisor(router, **{bad: 0})
    finally:
        router.close()


def test_wedged_member_quarantined_then_evicted_no_kill(tmp_path, mesh):
    """Missed heartbeats ALONE drive the eviction: no exception is
    raised, no kill_member is called — member 0 just stops answering
    probes, and the state machine walks healthy → wedged(quarantine)
    → evicted with the journaled FLEET.json record."""
    router = _router(tmp_path, mesh)
    sup = FleetSupervisor(router, heartbeat_misses=2, grace_ticks=1)
    try:
        assert _health(router, 0, "healthy") == 1.0
        router.members[0].scheduler.faults = ChaosInjector(
            ChaosPlan(wedge_member=0)
        )
        sup.tick()  # one miss: below the deadline, still healthy
        assert not router.members[0].quarantined
        assert router.members[0].health == "healthy"
        sup.tick()  # second miss: wedged — quarantined, NOT evicted
        assert router.members[0].quarantined
        assert router.members[0].health == "wedged"
        assert router.members[0].alive
        assert _health(router, 0, "wedged") == 1.0
        assert _health(router, 0, "healthy") == 0.0
        sup.tick()  # past grace_ticks: evicted
        assert not router.members[0].alive
        assert router.members[0].health == "evicted"
        assert _health(router, 0, "evicted") == 1.0
        assert sup._evictions_total.value(cause="wedged") == 1
        doc = FleetJournal(router.journal.dir).load()
        assert doc["evicted"] == {"0": {"cause": "wedged"}}
        # The healthy peers never left "healthy".
        assert all(m.alive for m in router.members[1:])
        assert _health(router, 1, "healthy") == 1.0
    finally:
        router.close()


def test_brownout_hysteresis_quarantine_restore(tmp_path, mesh):
    """The false-positive guard rails, driven on synthetic latency
    windows: a slow member is quarantined but survives a long grace
    window, and ``restore_ticks`` clean ticks lift the quarantine."""
    router = _router(tmp_path, mesh)
    sup = FleetSupervisor(
        router, slow_factor=3.0, window=4, grace_ticks=100,
        restore_ticks=2,
    )
    try:
        for m in router.members:
            m.scheduler.recent_quantum_seconds.extend([0.01] * 4)
        router.members[0].scheduler.recent_quantum_seconds.extend(
            [1.0] * 4
        )
        sup.tick()
        assert router.members[0].quarantined
        assert router.members[0].health == "brownout"
        assert _health(router, 0, "brownout") == 1.0
        # Quarantined members rank strictly last for new placements.
        req = synthetic_requests(mesh, 1, class_sizes=(24,))[0]
        assert router.member_of(router.submit(req)) != 0
        # Latency recovers: two clean ticks restore the member.
        router.members[0].scheduler.recent_quantum_seconds.extend(
            [0.01] * 4
        )
        sup.tick()
        assert router.members[0].quarantined  # one clean tick: held
        sup.tick()
        assert not router.members[0].quarantined
        assert router.members[0].health == "healthy"
        assert _health(router, 0, "healthy") == 1.0
        assert router.members[0].alive  # never evicted
        assert sup._evictions_total.value(cause="brownout") == 0
    finally:
        router.close()


def test_disk_pressure_classified_and_cooperatively_drained(
    tmp_path, mesh
):
    """An ENOSPC note on the member's journal flips the sticky
    degraded flag (gauge, no crash) and the supervisor walks it
    through quarantine to a COOPERATIVE drain."""
    router = _router(tmp_path, mesh)
    sup = FleetSupervisor(router, grace_ticks=1)
    try:
        router.members[0].scheduler.journal.note_disk_failure(
            "test", OSError(errno.ENOSPC, "No space left on device")
        )
        assert router.members[0].registry.gauge(
            "pumi_journal_degraded"
        ).value(member="m0") == 1.0
        sup.tick()
        assert router.members[0].quarantined
        assert router.members[0].health == "disk-pressured"
        sup.tick()  # grace exhausted
        assert not router.members[0].alive
        assert sup._evictions_total.value(cause="disk-pressured") == 1
        doc = FleetJournal(router.journal.dir).load()
        assert doc["evicted"] == {"0": {"cause": "disk-pressured"}}
    finally:
        router.close()


# --------------------------------------------------------------------- #
# Fast core: journal degraded mode (unit, no scheduler)
# --------------------------------------------------------------------- #
def test_journal_degrades_on_enospc_instead_of_crashing(tmp_path):
    j = SchedulerJournal(str(tmp_path / "j"))
    fired = []
    j.on_degraded = lambda op, exc: fired.append((op, exc.errno))
    assert not j.degraded
    # The injected provider raises ENOSPC on the first durable write:
    # write_flux must swallow it into the degraded flag, not raise.
    j.faults = ChaosInjector(ChaosPlan(disk_full_at=1))
    assert j.write_flux("job-a", np.ones(3, np.float64)) is None
    assert j.degraded
    assert fired == [("flux persist", errno.ENOSPC)]
    # Sticky + idempotent: further durable writes no-op quietly and
    # the callback does not re-fire.
    j.flush([], quantum_moves=2)
    assert j.write_flux("job-b", np.ones(3, np.float64)) is None
    assert fired == [("flux persist", errno.ENOSPC)]
    assert j.load() is None  # nothing ever hit the disk


def test_journal_non_disk_oserror_still_raises(tmp_path):
    """Only ENOSPC-class errnos degrade; a real I/O error (bad disk,
    not a full one) still propagates loudly."""
    j = SchedulerJournal(str(tmp_path / "j"))

    class _EIOFaults:
        def maybe_disk_full(self):
            raise OSError(errno.EIO, "I/O error")

    j.faults = _EIOFaults()
    with pytest.raises(OSError, match="I/O error"):
        j.write_flux("job-a", np.ones(3, np.float64))
    assert not j.degraded


# --------------------------------------------------------------------- #
# Fast core: eviction record replayed at recovery (crash mid-evict)
# --------------------------------------------------------------------- #
def test_eviction_record_replayed_at_recovery(tmp_path, mesh):
    """The crash window the protolint ordering exists for: the
    eviction record is journaled, the process dies BEFORE the drain —
    recovery must finish the drain from the member's on-disk journal,
    with zero orphaned and zero duplicated jobs."""
    fdir = str(tmp_path / "fleet")
    router = FleetRouter(
        mesh, _cfg(), fleet_dir=fdir, n_members=2, bank=None,
        quantum_moves=2, max_resident=2,
    )
    requests = synthetic_requests(mesh, 4, class_sizes=(24,))
    for r in requests:
        router.submit(r, idempotency_key=f"key-{r.job_id}")
    victims = [
        r.job_id for r in requests if router.member_of(r.job_id) == 0
    ]
    assert victims
    router.record_eviction(0, "wedged")
    router.abandon()  # crash model: record flushed, drain never ran

    router = FleetRouter.recover(
        fdir, mesh, _cfg(), bank=None, quantum_moves=2, max_resident=2,
    )
    try:
        # The evicted slot is never rebuilt; its jobs moved to the
        # survivor exactly once.
        assert not router.members[0].alive
        assert router.members[0].health == "evicted"
        for jid in victims:
            assert router.member_of(jid) == 1
        ids = sorted(j.id for j in router.jobs())
        assert ids == sorted(r.job_id for r in requests)
        doc = FleetJournal(fdir).load()
        assert doc["evicted"] == {"0": {"cause": "wedged"}}
        # The record survives a SECOND crash/recover cycle too — the
        # slot stays retired rather than resurrecting.
        router.abandon()
        router = FleetRouter.recover(
            fdir, mesh, _cfg(), bank=None, quantum_moves=2,
            max_resident=2,
        )
        assert not router.members[0].alive
        assert sorted(j.id for j in router.jobs()) == ids
    finally:
        router.close()


# --------------------------------------------------------------------- #
# Fast core: gateway deadlines + 503 backpressure guidance
# --------------------------------------------------------------------- #
def test_gateway_knob_validation(tmp_path, mesh):
    router = _router(tmp_path, mesh, n_members=1)
    try:
        with pytest.raises(ValueError, match="request_timeout_s"):
            TallyGateway(router, port=0, request_timeout_s=0)
        with pytest.raises(ValueError, match="retry_after_s"):
            TallyGateway(router, port=0, retry_after_s=-1)
    finally:
        router.close()


def test_gateway_503_retry_after_on_backpressure(tmp_path, mesh):
    """A saturated fleet (every member at max_queued) answers 503
    with the Retry-After header and jittered-backoff guidance, and
    does NOT journal the rejected idempotency key — the retry is a
    fresh acceptance once capacity returns."""
    router = _router(
        tmp_path, mesh, n_members=2, max_resident=1, max_queued=1,
    )
    gateway = TallyGateway(router, port=0, retry_after_s=2.5)
    try:
        for r in synthetic_requests(mesh, 4, class_sizes=(24,)):
            router.submit(r)  # 1 resident + 1 queued per member
        assert router.backpressured()
        from pumiumtally_tpu.serving.journal import request_to_json
        wire = request_to_json(
            synthetic_requests(mesh, 1, class_sizes=(24,))[0]
        )
        body = json.dumps(dict(wire, idempotency_key="key-z")).encode()
        req = urllib.request.Request(
            f"{gateway.url}/submit", data=body,
            headers={"Content-Type": "application/json"},
        )
        with pytest.raises(urllib.error.HTTPError) as exc_info:
            urllib.request.urlopen(req, timeout=30)
        e = exc_info.value
        payload = json.loads(e.read())
        assert e.code == 503
        assert e.headers["Retry-After"] == "3"  # ceil(2.5)
        assert payload["retry_after_s"] == 2.5
        assert payload["retry_jitter_s"] == 1.25
        assert "idempotency_key" in payload["guidance"]
        # The rejected key burned nothing.
        doc = FleetJournal(router.journal.dir).load()
        assert "key-z" not in doc["accepted"]
        # Per-request socket deadlines are live on the handler class.
        assert gateway.request_timeout_s == 30.0
    finally:
        gateway.stop()
        router.close()


# --------------------------------------------------------------------- #
# Fast core: fault grammar + teleview evicted link
# --------------------------------------------------------------------- #
def test_parse_faults_self_healing_clauses():
    plan = parse_faults("wedge_member:1")
    assert plan.wedge_member == 1
    plan = parse_faults("slow_member:2:8")
    assert (plan.slow_member, plan.slow_factor) == (2, 8.0)
    plan = parse_faults("slow_member:0")  # factor defaults to 4x
    assert (plan.slow_member, plan.slow_factor) == (0, 4.0)
    plan = parse_faults("disk_full_at:3")
    assert plan.disk_full_at == 3
    with pytest.raises(ValueError, match="factor must be >= 1"):
        parse_faults("slow_member:0:0.5")
    with pytest.raises(ValueError, match="durable writes from 1"):
        parse_faults("disk_full_at:0")
    # The unknown-clause message teaches the new grammar.
    with pytest.raises(ValueError) as exc_info:
        parse_faults("nope:1")
    for clause in ("wedge_member", "slow_member", "disk_full_at"):
        assert clause in str(exc_info.value)


def _rec(name, *, kind="span", sid, parent=None, pid=1, seq=0):
    return dict(
        schema=TRACE_SCHEMA, kind=kind, name=name, trace_id="t1",
        span_id=sid, parent_id=parent, job_id="jX", pid=pid, ts=1.0,
        seconds=0.0, seq=seq,
    )


def test_teleview_accepts_evicted_link():
    root = "t1/root"
    split = [
        _rec("submit", kind="event", sid="a", parent=root, seq=0),
        _rec("quantum", sid="b", parent=root, seq=1),
        _rec("quantum", sid="c", parent=root, pid=2, seq=2),
        _rec("job", sid=root, pid=2, seq=3),
    ]
    problems = check_job_trace(job_trace(split, "jX"), "jX")
    assert any("evicted" in p for p in problems)  # teaches the link
    healed = split + [
        _rec("evicted", kind="event", sid="d", parent=root, pid=2,
             seq=4)
    ]
    assert check_job_trace(job_trace(healed, "jX"), "jX") == []


# --------------------------------------------------------------------- #
# The slow half: end-to-end bitwise drills (real quanta)
# --------------------------------------------------------------------- #
@pytest.mark.slow
def test_wedged_eviction_end_to_end_bitwise(tmp_path, mesh):
    requests = synthetic_requests(mesh, 4, class_sizes=(24,), n_moves=6)
    ref = _reference_results(tmp_path, mesh, requests)
    router = _router(tmp_path, mesh, n_members=3)
    try:
        for r in requests:
            router.submit(r, idempotency_key=f"key-{r.job_id}")
        router.step()  # real checkpoints exist before the wedge
        victims = [
            r.job_id for r in requests
            if router.member_of(r.job_id) == 0
        ]
        assert victims
        router.members[0].scheduler.faults = ChaosInjector(
            ChaosPlan(wedge_member=0)
        )
        sup = FleetSupervisor(router, heartbeat_misses=2, grace_ticks=1)
        sup.run()
        assert not router.members[0].alive
        for jid in victims:
            assert router.member_of(jid) != 0
        ids = sorted(j.id for j in router.jobs())
        assert ids == sorted(r.job_id for r in requests)
        for r in requests:
            assert np.array_equal(
                np.asarray(router.result(r.job_id)), ref[r.job_id]
            ), f"{r.job_id} not bitwise across wedged eviction"
        # The hop is observable: evicted trace links for the victims.
        trace = [
            json.loads(line)
            for line in open(router.journal.trace_path())
            if line.strip()
        ]
        linked = {
            t["job_id"] for t in trace if t.get("name") == "evicted"
        }
        assert set(victims) <= linked
    finally:
        router.close()


@pytest.mark.slow
def test_brownout_quarantined_not_evicted_restored_bitwise(
    tmp_path, mesh
):
    """Satellite: false-positive resistance.  A 25x-slow member trips
    quarantine but never eviction; once the slowness clears it is
    restored and every job finishes bitwise WHERE IT WAS PLACED —
    zero migrations."""
    requests = synthetic_requests(mesh, 4, class_sizes=(24,), n_moves=6)
    ref = _reference_results(tmp_path, mesh, requests, quantum_moves=1)
    router = _router(tmp_path, mesh, n_members=3, quantum_moves=1)
    try:
        for r in requests:
            router.submit(r, idempotency_key=f"key-{r.job_id}")
        router.members[0].scheduler.faults = ChaosInjector(
            ChaosPlan(slow_member=0, slow_factor=25.0)
        )
        sup = FleetSupervisor(
            router, slow_factor=4.0, window=2, grace_ticks=50,
            restore_ticks=1,
        )
        quarantined_seen = False
        for _ in range(200):
            pending = router.step()
            sup.tick()
            if router.members[0].quarantined and not quarantined_seen:
                quarantined_seen = True
                # The transient clears: drop the injection.
                router.members[0].scheduler.faults = ChaosInjector(
                    ChaosPlan()
                )
            if not pending and all(
                j.terminal for j in router.jobs()
            ):
                break
        assert quarantined_seen
        assert all(m.alive for m in router.members)  # never evicted
        assert not router.members[0].quarantined  # restored
        assert router.members[0].health == "healthy"
        assert router.stats()["migrations"] == 0
        for r in requests:
            assert np.array_equal(
                np.asarray(router.result(r.job_id)), ref[r.job_id]
            ), f"{r.job_id} not bitwise through quarantine"
    finally:
        router.close()


@pytest.mark.slow
def test_disk_pressure_drained_zero_loss_bitwise(tmp_path, mesh):
    requests = synthetic_requests(mesh, 4, class_sizes=(24,), n_moves=6)
    ref = _reference_results(tmp_path, mesh, requests)
    router = _router(tmp_path, mesh, n_members=2)
    try:
        for r in requests:
            router.submit(r, idempotency_key=f"key-{r.job_id}")
        router.members[0].scheduler.faults = ChaosInjector(
            ChaosPlan(disk_full_at=1)
        )
        sup = FleetSupervisor(router, grace_ticks=1)
        sup.run()
        assert router.members[0].registry.gauge(
            "pumi_journal_degraded"
        ).value(member="m0") == 1.0
        assert not router.members[0].alive
        assert router.members[0].health == "evicted"
        doc = FleetJournal(router.journal.dir).load()
        assert doc["evicted"] == {"0": {"cause": "disk-pressured"}}
        ids = sorted(j.id for j in router.jobs())
        assert ids == sorted(r.job_id for r in requests)
        for r in requests:
            assert np.array_equal(
                np.asarray(router.result(r.job_id)), ref[r.job_id]
            ), f"{r.job_id} not bitwise across disk-pressure drain"
    finally:
        router.close()
