"""Graft-check layer 4 tests: durability & concurrency protocol lint.

Two halves, mirroring tests/test_static_analysis.py's contract:

  * the four layer-4 AST rules (PUMI008 raw durable writes, PUMI009
    signal-handler safety, PUMI010 unguarded thread-shared state,
    PUMI011 swallowed retryables) each fire on a positive fixture and
    stay quiet on the sanctioned idiom beside it;
  * the effect-ordering protocol analyzer (analysis/protolint.py) is
    exercised against the REAL tree with injected regressions — the
    journal-commit/checkpoint-delete reorder, the stale-handler
    clobber, an early manifest commit — and each produces its NAMED
    finding; plus baseline routing, cross-env refusal, --explain, and
    the repo-stays-clean pins.
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
from pathlib import Path

import pytest

from pumiumtally_tpu.analysis import apply_baseline, load_baseline
from pumiumtally_tpu.analysis import protolint as P
from pumiumtally_tpu.analysis.astlint import (
    explain,
    lint_package,
    lint_sources,
)

ROOT = Path(__file__).resolve().parents[1]


def at(findings, rule):
    return [f for f in findings if f.rule == rule]


# --------------------------------------------------------------------- #
# PUMI008: raw durable writes
# --------------------------------------------------------------------- #
def test_raw_write_fires_outside_approved_modules():
    src = """
import json

def persist(path, state):
    with open(path, "w") as fh:
        json.dump(state, fh)
"""
    fs = lint_sources({"pumiumtally_tpu/serving/fake.py": src})
    found = at(fs, "PUMI008")
    # ONE finding — the open; the json.dump through the open handle is
    # the same write, not a second one.
    assert len(found) == 1, [f.render() for f in found]
    assert found[0].symbol == "persist"
    assert 'open(..., "w")' in found[0].message


def test_raw_write_quiet_in_approved_module():
    src = """
import json

def flush(path, doc):
    with open(path, "w") as fh:
        json.dump(doc, fh)
"""
    fs = lint_sources({"pumiumtally_tpu/serving/journal.py": src})
    assert at(fs, "PUMI008") == []


def test_np_save_to_bytesio_is_in_memory_and_clean():
    src = """
import io
import numpy as np

def pack(arr):
    buf = io.BytesIO()
    np.save(buf, arr)
    return buf.getvalue()
"""
    fs = lint_sources({"pumiumtally_tpu/serving/fake.py": src})
    assert at(fs, "PUMI008") == []


def test_np_save_to_path_and_write_text_fire():
    src = """
import numpy as np

def persist(path, arr, meta):
    np.save(path, arr)
    path.write_text(meta)
"""
    fs = lint_sources({"pumiumtally_tpu/obs/fake.py": src})
    assert len(at(fs, "PUMI008")) == 2


def test_inline_open_oneliner_reports_once():
    """``json.dump(obj, open(p, "w"))`` is ONE write, not two — the
    inline open carries the finding and the dump is suppressed."""
    src = """
import json

def persist(path, state):
    json.dump(state, open(path, "w"))
"""
    fs = lint_sources({"pumiumtally_tpu/serving/fake.py": src})
    found = at(fs, "PUMI008")
    assert len(found) == 1, [f.render() for f in found]
    assert 'open(..., "w")' in found[0].message


def test_class_body_raw_write_fires():
    """Import-time writes in class bodies are scanned too — they are
    not covered by index.defs and would otherwise be a blind spot."""
    src = """
import json

class Config:
    _default = json.dump({"x": 1}, open("cfg.json", "w"))
"""
    fs = lint_sources({"pumiumtally_tpu/serving/fake.py": src})
    assert len(at(fs, "PUMI008")) == 1, [f.render() for f in fs]


def test_read_mode_open_is_clean():
    src = """
import json

def load(path):
    with open(path) as fh:
        return json.load(fh)
"""
    fs = lint_sources({"pumiumtally_tpu/serving/fake.py": src})
    assert at(fs, "PUMI008") == []


def test_journal_scripts_get_durability_rule_other_scripts_dont():
    src = """
import json

def dump(path, state):
    with open(path, "w") as fh:
        json.dump(state, fh)
"""
    fs = lint_sources({"scripts/serve.py": src})
    assert len(at(fs, "PUMI008")) == 1
    fs = lint_sources({"scripts/chaos_serve.py": src})
    assert len(at(fs, "PUMI008")) == 1
    # other scripts keep the value-safety subset only
    fs = lint_sources({"scripts/teleview.py": src})
    assert at(fs, "PUMI008") == []


# --------------------------------------------------------------------- #
# PUMI009: signal-handler safety
# --------------------------------------------------------------------- #
_HANDLER_TMPL = """
from ..utils.signals import (
    install_preemption_handlers,
    uninstall_preemption_handlers,
    resume_previous_handler,
)

class Supervisor:
    def __init__(self):
        self._in_step = False
        self._pending_signal = None
        self._prev = install_preemption_handlers(self._on_signal, "S")

    def _flush_journal(self):
        pass

    def _on_signal(self, signum, frame):
{guard}        self._flush(signum, frame)

    def _flush(self, signum, frame):
        self._flush_journal()
        uninstall_preemption_handlers(self._prev, mine=self._on_signal)
        resume_previous_handler(self._prev.get(signum), signum, frame)

    def close(self):
        uninstall_preemption_handlers(self._prev, mine=self._on_signal)
"""

_GUARD = (
    "        if self._in_step:\n"
    "            self._pending_signal = signum\n"
    "            return\n"
)


def _signals_stub():
    return {
        "pumiumtally_tpu/utils/signals.py": (
            (ROOT / "pumiumtally_tpu/utils/signals.py").read_text()
        ),
        "pumiumtally_tpu/utils/log.py": (
            (ROOT / "pumiumtally_tpu/utils/log.py").read_text()
        ),
    }


def test_handler_journal_flush_without_deferral_guard_fires():
    src = _HANDLER_TMPL.format(guard="")
    fs = lint_sources(
        {**_signals_stub(), "pumiumtally_tpu/serving/fake.py": src}
    )
    found = at(fs, "PUMI009")
    assert found, [f.render() for f in fs]
    assert any("deferral guard" in f.message for f in found)


def test_handler_journal_flush_with_deferral_guard_is_clean():
    src = _HANDLER_TMPL.format(guard=_GUARD)
    fs = lint_sources(
        {**_signals_stub(), "pumiumtally_tpu/serving/fake.py": src}
    )
    assert at(fs, "PUMI009") == [], [f.render() for f in fs]


def test_handler_taking_annotated_lock_fires():
    src = """
import threading

from ..utils.signals import (
    install_preemption_handlers,
    uninstall_preemption_handlers,
)

class Supervisor:
    def __init__(self):
        self._lock = threading.Lock()
        self._state = 0  # guarded by: self._lock
        self._prev = install_preemption_handlers(self._on_signal, "S")

    def _on_signal(self, signum, frame):
        with self._lock:
            self._state += 1

    def close(self):
        uninstall_preemption_handlers(self._prev, mine=self._on_signal)
"""
    fs = lint_sources(
        {**_signals_stub(), "pumiumtally_tpu/obs/fake.py": src}
    )
    found = at(fs, "PUMI009")
    assert any("deadlock" in f.message for f in found), [
        f.render() for f in fs
    ]


def test_install_without_any_uninstall_fires():
    src = """
from ..utils.signals import install_preemption_handlers

class Supervisor:
    def __init__(self):
        self._prev = install_preemption_handlers(self._on_signal, "S")

    def _on_signal(self, signum, frame):
        pass
"""
    fs = lint_sources(
        {**_signals_stub(), "pumiumtally_tpu/obs/fake.py": src}
    )
    found = at(fs, "PUMI009")
    assert any("matching uninstall" in f.message for f in found)


def test_resume_without_uninstall_fires():
    src = """
from ..utils.signals import (
    install_preemption_handlers,
    uninstall_preemption_handlers,
    resume_previous_handler,
)

class Supervisor:
    def __init__(self):
        self._prev = install_preemption_handlers(self._on_signal, "S")

    def _on_signal(self, signum, frame):
        resume_previous_handler(self._prev.get(signum), signum, frame)

    def close(self):
        uninstall_preemption_handlers(self._prev, mine=self._on_signal)
"""
    fs = lint_sources(
        {**_signals_stub(), "pumiumtally_tpu/obs/fake.py": src}
    )
    found = at(fs, "PUMI009")
    assert any("stale handler" in f.message for f in found)


def test_real_scheduler_without_deferral_guard_fires():
    """Injected regression on the REAL tree: strip the scheduler
    handler's mid-quantum deferral — its journal flush must become a
    named PUMI009 finding."""
    sched = "pumiumtally_tpu/serving/scheduler.py"
    srcs = {
        p: (ROOT / p).read_text()
        for p in (sched, "pumiumtally_tpu/utils/signals.py",
                  "pumiumtally_tpu/utils/log.py")
    }
    guard = (
        "        if self._in_step:\n"
        "            # Mid-quantum: defer to the quantum boundary so the\n"
        "            # flushed checkpoints are consistent post-dispatch states.\n"
        "            self._pending_signal = signum\n"
        "            return\n"
    )
    assert guard in srcs[sched]
    bad = srcs[sched].replace(guard, "")
    fs = lint_sources({**srcs, sched: bad})
    found = [
        f for f in at(fs, "PUMI009") if "deferral" in f.message
    ]
    assert found, [f.render() for f in at(fs, "PUMI009")]


# --------------------------------------------------------------------- #
# PUMI010: unguarded thread-shared state
# --------------------------------------------------------------------- #
def test_unannotated_attr_written_from_thread_target_fires():
    src = """
import threading

class Watcher:
    def __init__(self):
        self._beat = 0
        self._thread = threading.Thread(target=self._run, daemon=True)

    def _run(self):
        self._beat += 1
"""
    fs = lint_sources({"pumiumtally_tpu/obs/fake.py": src})
    found = at(fs, "PUMI010")
    assert len(found) == 1 and "_beat" in found[0].message


def test_annotated_attr_written_from_thread_target_is_clean():
    src = """
import threading

class Watcher:
    def __init__(self):
        self._lock = threading.Lock()
        self._beat = 0  # guarded by: self._lock
        self._thread = threading.Thread(target=self._run, daemon=True)

    def _run(self):
        with self._lock:
            self._beat += 1
"""
    fs = lint_sources({"pumiumtally_tpu/obs/fake.py": src})
    assert at(fs, "PUMI010") == []


def test_worker_closure_writing_shared_local_fires_unless_annotated():
    bad = """
import threading

def run(fn):
    outcome = {}

    def target():
        outcome["value"] = fn()

    threading.Thread(target=target).start()
    return outcome
"""
    fs = lint_sources({"pumiumtally_tpu/obs/fake.py": bad})
    found = at(fs, "PUMI010")
    assert len(found) == 1 and "outcome" in found[0].message

    good = bad.replace(
        "    outcome = {}",
        "    finished = threading.Event()\n"
        "    outcome = {}  # guarded by: finished (event)",
    ).replace(
        'outcome["value"] = fn()',
        'outcome["value"] = fn()\n        finished.set()',
    ).replace(
        "    return outcome",
        "    finished.wait(1.0)\n    return outcome",
    )
    fs = lint_sources({"pumiumtally_tpu/obs/fake.py": good})
    assert at(fs, "PUMI010") == [], [f.render() for f in fs]


def test_worker_shadowing_local_is_thread_confined_and_clean():
    """A plain-name rebind in the worker creates a WORKER-LOCAL (no
    nonlocal declared) — merely shadowing an enclosing-scope name
    shares nothing and must not be flagged."""
    src = """
import threading

def run(fn):
    buf = None

    def target():
        buf = []
        buf.append(fn())

    threading.Thread(target=target).start()
    return buf
"""
    fs = lint_sources({"pumiumtally_tpu/obs/fake.py": src})
    assert at(fs, "PUMI010") == [], [f.render() for f in fs]


def test_worker_nonlocal_rebind_fires():
    src = """
import threading

def run(fn):
    result = None

    def target():
        nonlocal result
        result = fn()

    threading.Thread(target=target).start()
    return result
"""
    fs = lint_sources({"pumiumtally_tpu/obs/fake.py": src})
    found = at(fs, "PUMI010")
    assert len(found) == 1 and "result" in found[0].message


def test_executor_worker_writing_attr_fires():
    src = """
from concurrent.futures import ThreadPoolExecutor

class Sharder:
    def write_all(self, n):
        with ThreadPoolExecutor(max_workers=4) as ex:
            list(ex.map(self._write_one, range(n)))

    def _write_one(self, i):
        self._last_written = i
"""
    fs = lint_sources({"pumiumtally_tpu/obs/fake.py": src})
    found = at(fs, "PUMI010")
    assert len(found) == 1 and "_last_written" in found[0].message


# --------------------------------------------------------------------- #
# PUMI011: swallowed retryables
# --------------------------------------------------------------------- #
def test_swallowed_retryable_fires():
    src = """
from ..resilience.faultinject import InjectedTransientFault

def run(body):
    try:
        return body()
    except InjectedTransientFault:
        return None
"""
    fs = lint_sources({"pumiumtally_tpu/serving/fake.py": src})
    found = at(fs, "PUMI011")
    assert len(found) == 1
    assert "InjectedTransientFault" in found[0].message


@pytest.mark.parametrize(
    "handler",
    [
        "        raise",
        "        verdict = coordinator.classify(e)\n        return verdict",
        "        counter.inc(cause='transient')\n        return None",
    ],
    ids=["reraise", "classify", "metric"],
)
def test_retryable_with_sanctioned_route_is_clean(handler):
    src = f"""
from ..resilience.faultinject import InjectedTransientFault

def run(body, coordinator, counter):
    try:
        return body()
    except InjectedTransientFault as e:
{handler}
"""
    fs = lint_sources({"pumiumtally_tpu/serving/fake.py": src})
    assert at(fs, "PUMI011") == [], [f.render() for f in fs]


def test_nonretryable_except_is_not_flagged():
    src = """
def run(body):
    try:
        return body()
    except (OSError, ValueError):
        return None
"""
    fs = lint_sources({"pumiumtally_tpu/serving/fake.py": src})
    assert at(fs, "PUMI011") == []


# --------------------------------------------------------------------- #
# Protocol analyzer: injected regressions on the real tree
# --------------------------------------------------------------------- #
SCHED = "pumiumtally_tpu/serving/scheduler.py"
CKPT = "pumiumtally_tpu/utils/checkpoint.py"


#: The protocol owners — indexing just the crash-safety modules keeps
#: each injected-regression check fast while still exercising the REAL
#: sources (every declared protocol lives in one of these files).
_CRASH_SAFETY_MODULES = (
    "pumiumtally_tpu/serving/scheduler.py",
    "pumiumtally_tpu/serving/journal.py",
    "pumiumtally_tpu/serving/fleet.py",
    "pumiumtally_tpu/serving/supervisor.py",
    "pumiumtally_tpu/resilience/runner.py",
    "pumiumtally_tpu/resilience/store.py",
    "pumiumtally_tpu/utils/checkpoint.py",
    "pumiumtally_tpu/utils/signals.py",
    "pumiumtally_tpu/utils/log.py",
)


@pytest.fixture(scope="module")
def real_sources():
    return {p: (ROOT / p).read_text() for p in _CRASH_SAFETY_MODULES}


def test_protocols_hold_on_the_real_tree(real_sources):
    assert P.check_sources(real_sources) == []


def test_reordered_finish_is_a_named_protocol_finding(real_sources):
    """THE acceptance regression: swap _finish's terminal journal
    flush and checkpoint delete — the exact ordering bug PR 14's
    review caught by hand must now be a named, machine-checked
    finding."""
    good = (
        "        self._flush_journal()\n"
        "        self._remove_checkpoint(job)\n"
    )
    src = real_sources[SCHED]
    assert good in src
    bad = src.replace(
        good,
        "        self._remove_checkpoint(job)\n"
        "        self._flush_journal()\n",
    )
    fs = P.check_sources({**real_sources, SCHED: bad})
    assert "order.terminal-record-before-checkpoint-delete" in {
        f.symbol for f in fs
    }, [f.render() for f in fs]


def test_stale_handler_clobber_is_a_named_protocol_finding(real_sources):
    src = real_sources[SCHED]
    pair = (
        "        self._uninstall_signal_handlers()\n"
        "        resume_previous_handler(prev, signum, frame)"
    )
    assert pair in src
    bad = src.replace(
        pair, "        resume_previous_handler(prev, signum, frame)"
    )
    fs = P.check_sources({**real_sources, SCHED: bad})
    syms = {f.symbol for f in fs}
    assert "order.scheduler-uninstall-before-resume" in syms or (
        "require.scheduler-uninstall-before-resume" in syms
    ), [f.render() for f in fs]


def test_early_manifest_commit_is_a_named_protocol_finding(real_sources):
    src = real_sources[CKPT]
    anchor = "    from concurrent.futures import ThreadPoolExecutor"
    assert anchor in src
    bad = src.replace(
        anchor,
        "    atomic_write_bytes(\n"
        "        manifest_path, json.dumps({}).encode()\n"
        "    )\n" + anchor,
    )
    fs = P.check_sources({**real_sources, CKPT: bad})
    assert "order.manifest-commit-last" in {f.symbol for f in fs}, [
        f.render() for f in fs
    ]


def test_raw_journal_flush_is_a_named_protocol_finding(real_sources):
    """Replace the journal document's atomic write with a raw one —
    both the forbid (raw.write) and require (atomic.write) halves of
    journal-document-atomic must fire."""
    jr = "pumiumtally_tpu/serving/journal.py"
    src = real_sources[jr]
    atomic = "            atomic_write_json(self.path, doc)"
    assert atomic in src
    bad = src.replace(
        atomic,
        "            with open(self.path, \"w\") as fh:\n"
        "                json.dump(doc, fh)",
    )
    fs = P.check_sources({**real_sources, jr: bad})
    syms = {f.symbol for f in fs}
    assert "forbid.journal-document-atomic" in syms, [
        f.render() for f in fs
    ]
    assert "require.journal-document-atomic" in syms


def test_reordered_eviction_record_is_a_named_protocol_finding(
    real_sources,
):
    """Move the supervisor's FLEET.json eviction record AFTER the
    drain — the crash window ISSUE 19's ordering exists to close
    (record-less drain: re-placed jobs under a member the routing
    journal still calls healthy) must be a named finding on every
    CFG path through ``_evict``."""
    sup = "pumiumtally_tpu/serving/supervisor.py"
    src = real_sources[sup]
    record = "        self.router.record_eviction(member.index, cause)\n"
    counter = "        self._evictions_total.inc(cause=cause)\n"
    assert record in src and counter in src
    bad = src.replace(record, "").replace(counter, record + counter)
    fs = P.check_sources({**real_sources, sup: bad})
    assert "order.eviction-record-before-drain" in {
        f.symbol for f in fs
    }, [f.render() for f in fs]


def test_path_explosion_is_flagged_not_silently_truncated(real_sources):
    """A protocol owner whose CFG outgrows MAX_PATHS must produce a
    named paths.* finding — the constraints were only checked on a
    prefix, and 'partially verified' must never read as clean."""
    branches = "".join(
        "        if job:\n"
        "            fsync_dir(self.dir)\n"
        "        else:\n"
        "            atomic_savez(self.dir)\n"
        for _ in range(10)  # 2**10 distinct effect paths > MAX_PATHS
    )
    src = (
        "import os\n\n"
        "class TallyScheduler:\n"
        "    def _finish(self, job, outcome):\n"
        + branches
        + "        self._flush_journal()\n"
        "        self._remove_checkpoint(job)\n"
    )
    fs = P.check_sources(
        {"pumiumtally_tpu/serving/scheduler.py": src}
    )
    assert "paths.terminal-record-before-checkpoint-delete" in {
        f.symbol for f in fs
    }, [f.render() for f in fs]


def test_missing_owner_function_is_reported(real_sources):
    bad = real_sources[SCHED].replace(
        "    def _poison(", "    def _poison_renamed("
    )
    fs = P.check_sources({**real_sources, SCHED: bad})
    assert "missing.poison-record-before-checkpoint-delete" in {
        f.symbol for f in fs
    }


# --------------------------------------------------------------------- #
# PROTOCOLS.json: capture, drift, cross-env refusal
# --------------------------------------------------------------------- #
def test_diff_baseline_names_drift_and_refuses_cross_env(real_sources):
    index = P.index_from_sources(real_sources)
    cap = P.capture(index)
    base = json.loads(json.dumps(cap))
    assert P.diff_baseline(cap, base) == []

    tampered = json.loads(json.dumps(base))
    name = "terminal-record-before-checkpoint-delete"
    tampered["protocols"][name]["effects"]["checkpoint.delete"] = 7
    syms = {f.symbol for f in P.diff_baseline(cap, tampered)}
    assert f"drift.{name}" in syms

    other_env = json.loads(json.dumps(base))
    other_env["environment"]["n_devices"] = 1234
    syms = {f.symbol for f in P.diff_baseline(cap, other_env)}
    assert syms == {"environment.all"}

    removed = json.loads(json.dumps(base))
    del removed["protocols"][name]
    syms = {f.symbol for f in P.diff_baseline(cap, removed)}
    assert f"protocol.added.{name}" in syms


def test_committed_protocols_json_matches_declarations():
    """The committed capture must cover exactly the declared protocol
    set (the env-sensitive diff itself runs in the canonical
    subprocess below)."""
    committed = json.loads((ROOT / "PROTOCOLS.json").read_text())
    assert committed["schema"] == P.PROTOCOLS_SCHEMA
    assert set(committed["protocols"]) == {p.name for p in P.PROTOCOLS}
    for name, rec in committed["protocols"].items():
        assert rec["effects"], f"{name} captured no effects"


# --------------------------------------------------------------------- #
# Runner integration: baseline routing, --explain, repo stays clean
# --------------------------------------------------------------------- #
def _run_lint(*flags, timeout=300):
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)  # the runner pins its own
    return subprocess.run(
        [sys.executable, str(ROOT / "scripts" / "lint.py"), *flags],
        capture_output=True, text=True, env=env, cwd=str(ROOT),
        timeout=timeout,
    )


@pytest.mark.slow  # subprocess spawn; CI's dedicated protocol-lint /
# static-analysis steps enforce the same gate on every run
def test_protocols_only_runner_exits_clean():
    """scripts/lint.py --protocols-only (fresh process, canonical
    environment) must exit 0 against the committed PROTOCOLS.json."""
    proc = _run_lint("--protocols-only")
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "protolint: clean" in proc.stdout


@pytest.mark.slow  # subprocess spawn; CI's dedicated protocol-lint /
# static-analysis steps enforce the same gate on every run
def test_stale_proto_baseline_entry_hard_fails(tmp_path):
    committed = json.loads(
        (ROOT / "LINT_BASELINE.json").read_text()
    )["suppressions"]
    stale = {"rule": "PROTO", "path": "PROTOCOLS.json",
             "symbol": "order.long-gone-protocol",
             "justification": "retired two PRs ago"}
    p = tmp_path / "baseline.json"
    p.write_text(json.dumps({"suppressions": committed + [stale]}))
    proc = _run_lint("--protocols-only", "--baseline", str(p))
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert "stale baseline entry" in proc.stdout
    assert "long-gone-protocol" in proc.stdout


def test_proto_baseline_entry_routes_to_protocol_layer():
    from pumiumtally_tpu.analysis import Finding

    f = Finding("PROTO", "PROTOCOLS.json", 0,
                "order.terminal-record-before-checkpoint-delete", "m")
    entries = [{"rule": "PROTO", "path": "PROTOCOLS.json",
                "symbol": "order.terminal-record-before-checkpoint-delete",
                "justification": "test"}]
    kept, suppressed, unused = apply_baseline([f], entries)
    assert kept == [] and len(suppressed) == 1 and unused == []


@pytest.mark.slow  # subprocess spawn; CI's dedicated protocol-lint /
# static-analysis steps enforce the same gate on every run
def test_explain_rule_and_protocol():
    proc = _run_lint("--explain", "PUMI008")
    assert proc.returncode == 0, proc.stderr
    for token in ("Rationale", "Example finding", "Fix pattern"):
        assert token in proc.stdout
    proc = _run_lint(
        "--explain", "terminal-record-before-checkpoint-delete"
    )
    assert proc.returncode == 0
    assert "Rationale" in proc.stdout and "Constraints" in proc.stdout
    proc = _run_lint("--explain", "protocol")
    assert proc.returncode == 0
    assert "manifest-commit-last" in proc.stdout
    proc = _run_lint("--explain", "NOPE999")
    assert proc.returncode == 2
    assert "unknown rule" in proc.stderr


def test_write_protocols_for_disabled_layer_is_rejected():
    proc = _run_lint("--ast-only", "--write-protocols")
    assert proc.returncode == 2, proc.stdout + proc.stderr
    assert "needs the" in proc.stderr


@pytest.mark.slow  # subprocess spawn; CI's dedicated protocol-lint /
# static-analysis steps enforce the same gate on every run
def test_repo_layer4_rules_clean_modulo_baseline():
    findings = lint_package(ROOT)
    entries = load_baseline(ROOT / "LINT_BASELINE.json")
    kept, _, _ = apply_baseline(findings, entries)
    layer4 = [
        f for f in kept
        if f.rule in ("PUMI008", "PUMI009", "PUMI010", "PUMI011")
    ]
    assert layer4 == [], "\n".join(f.render() for f in layer4)


def test_explain_covers_every_rule():
    for rule in (
        "PUMI001", "PUMI002", "PUMI003", "PUMI004", "PUMI005",
        "PUMI006", "PUMI007", "PUMI008", "PUMI009", "PUMI010",
        "PUMI011",
    ):
        text = explain(rule)
        assert text and rule in text
    assert explain("PUMI999") is None
