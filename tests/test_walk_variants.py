"""The walk's performance variants (packed geo20 body, unroll, compaction
schedules) must be bit-equivalent to the unpacked four-gather baseline —
they change scheduling and op shapes, never semantics.

This pins BOTH walk bodies explicitly: the packed one-gather body (the
default whenever the mesh fits the packing limits) and the unpacked
fallback every mesh with >=2^24 elements or >64 class ids will take
(mesh/core.py:can_pack_walk_tables)."""
from __future__ import annotations

import numpy as np
import pytest

import jax.numpy as jnp

from pumiumtally_tpu import make_flux
from pumiumtally_tpu.mesh.box import build_box_arrays
from pumiumtally_tpu.mesh.core import TetMesh, can_pack_walk_tables
from pumiumtally_tpu.ops.walk import trace_impl


def _particles(mesh, n=96, seed=0):
    rng = np.random.default_rng(seed)
    elem = jnp.asarray(rng.integers(0, mesh.ntet, n).astype(np.int32))
    origin = jnp.asarray(
        np.asarray(mesh.centroids())[np.asarray(elem)], jnp.float32
    )
    dest = jnp.asarray(rng.uniform(-0.1, 1.1, (n, 3)), jnp.float32)
    return (
        mesh, origin, dest, elem,
        jnp.ones(n, bool),
        jnp.asarray(rng.uniform(0.5, 2.0, n), jnp.float32),
        jnp.asarray(rng.integers(0, 2, n), jnp.int32),
        jnp.full(n, -1, jnp.int32),
    )


@pytest.fixture(scope="module")
def setup():
    coords, tets = build_box_arrays(1.0, 1.0, 1.0, 4, 4, 4)
    cid = (coords[tets].mean(axis=1)[:, 0] > 0.5).astype(np.int32)
    mesh = TetMesh.from_numpy(coords, tets, cid, dtype=jnp.float32)
    assert mesh.geo20 is not None  # packed body is the default under test
    mesh_unpacked = TetMesh.from_numpy(
        coords, tets, cid, dtype=jnp.float32, packed=False
    )
    assert mesh_unpacked.geo20 is None
    args = _particles(mesh)
    kw = dict(initial=False, max_crossings=mesh.ntet + 8, tolerance=1e-6)
    base = trace_impl(*args, make_flux(mesh.ntet, 2, jnp.float32), **kw)
    return mesh, mesh_unpacked, args, kw, base


def _assert_same(got, base, flux_exact=True):
    if flux_exact:
        np.testing.assert_array_equal(
            np.asarray(got.flux), np.asarray(base.flux)
        )
    else:
        np.testing.assert_allclose(
            np.asarray(got.flux), np.asarray(base.flux), atol=1e-5, rtol=1e-5
        )
    np.testing.assert_array_equal(np.asarray(got.elem), np.asarray(base.elem))
    np.testing.assert_array_equal(
        np.asarray(got.material_id), np.asarray(base.material_id)
    )
    np.testing.assert_allclose(
        np.asarray(got.position), np.asarray(base.position), atol=1e-6
    )
    assert int(got.n_segments) == int(base.n_segments)
    assert bool(np.asarray(got.done).all())


def test_track_length_ledger(setup):
    """TraceResult.track_length is the per-particle conservation ledger:
    it must equal the net straight-line displacement (all movement is
    along the ray), and weighted by particle weight it must sum to the
    Σc flux total (every scored segment lands in exactly one bin)."""
    mesh, _, args, kw, base = setup
    tl = np.asarray(base.track_length)
    disp = np.linalg.norm(
        np.asarray(base.position) - np.asarray(args[1]), axis=1
    )
    np.testing.assert_allclose(tl, disp, atol=5e-6)
    w = np.asarray(args[5])
    np.testing.assert_allclose(
        np.asarray(base.flux[..., 0]).sum(), (tl * w).sum(), rtol=1e-5
    )


def test_unpacked_fallback_matches_packed(setup):
    """The four-gather fallback body must produce BIT-IDENTICAL results to
    the packed geo20 body — same floating-point operations, different table
    encodings (round-2 test debt, VERDICT item 3a)."""
    mesh, mesh_unpacked, args, kw, base = setup
    got = trace_impl(
        mesh_unpacked, *args[1:], make_flux(mesh.ntet, 2, jnp.float32), **kw
    )
    _assert_same(got, base, flux_exact=True)


@pytest.mark.parametrize("body", ["packed", "unpacked"])
def test_robust_off_matches_on_clean_mesh(setup, body):
    """robust=False (reference-parity truncate mode) drops the recovery
    machinery but must be BIT-IDENTICAL on a well-behaved mesh: the
    entry-face mask / chase / bump only ever fire on degeneracies, which a
    regular box has none of. Pins that the fast path's arithmetic is the
    same, not merely close."""
    mesh, mesh_unpacked, args, kw, base = setup
    m = mesh if body == "packed" else mesh_unpacked
    got = trace_impl(
        m, *args[1:], make_flux(mesh.ntet, 2, jnp.float32), **kw,
        robust=False,
    )
    _assert_same(got, base, flux_exact=True)


@pytest.mark.parametrize(
    "knob",
    [dict(tally_scatter="interleaved"), dict(gathers="split"),
     dict(tally_scatter="interleaved", gathers="split"),
     dict(ledger=False)],
    ids=["interleaved-scatter", "split-gathers", "both", "no-ledger"],
)
def test_scatter_gather_strategies_bit_identical(setup, knob):
    """The tally-scatter strategy (one interleaved 2m-row scatter vs a
    pair of m-row scatters — disjoint flat slots, so no accumulation
    reorder) and the packed-table read strategy (one 20-wide gather vs
    split 16+4) are pure scheduling choices: results must be
    BIT-identical to the default."""
    mesh, mesh_unpacked, args, kw, base = setup
    got = trace_impl(
        mesh, *args[1:], make_flux(mesh.ntet, 2, jnp.float32), **kw, **knob
    )
    _assert_same(got, base, flux_exact=True)
    if knob.get("ledger", True):
        np.testing.assert_array_equal(
            np.asarray(got.track_length), np.asarray(base.track_length)
        )
    else:
        assert got.track_length is None


@pytest.mark.parametrize("body", ["packed", "unpacked"])
def test_score_squares_off_drops_only_squares(setup, body):
    """score_squares=False (public config knob) must leave the Σc column
    identical and the Σc² column zero, in both walk bodies."""
    mesh, mesh_unpacked, args, kw, base = setup
    m = mesh if body == "packed" else mesh_unpacked
    got = trace_impl(
        m, *args[1:], make_flux(mesh.ntet, 2, jnp.float32), **kw,
        score_squares=False,
    )
    np.testing.assert_array_equal(
        np.asarray(got.flux[..., 0]), np.asarray(base.flux[..., 0])
    )
    assert not np.asarray(got.flux[..., 1]).any()
    assert int(got.n_segments) == int(base.n_segments)


@pytest.mark.parametrize(
    "variant",
    [
        dict(unroll=4),
        # compact/stage-unroll are the compile-heaviest variants; the
        # "stages" row keeps staged-compaction parity in the fast suite.
        pytest.param(
            dict(unroll=8, compact_after=4, compact_size=32),
            marks=pytest.mark.slow,
        ),
        dict(compact_stages=((4, 64), (8, 48), (16, 24)), unroll=2),
        pytest.param(
            dict(compact_stages=((4, 64), (8, 48, 4), (16, 24, 8)), unroll=2),
            marks=pytest.mark.slow,
        ),
    ],
    ids=["unroll", "compact", "stages", "stage-unroll"],
)
@pytest.mark.parametrize("body", ["packed", "unpacked"])
def test_variant_matches_baseline(setup, variant, body):
    mesh, mesh_unpacked, args, kw, base = setup
    m = mesh if body == "packed" else mesh_unpacked
    got = trace_impl(
        m, *args[1:], make_flux(mesh.ntet, 2, jnp.float32), **kw, **variant
    )
    # Compaction reorders the scatter accumulation ⇒ allclose, not equal.
    _assert_same(got, base, flux_exact=False)


def test_mixed_dtype_particles_on_f32_mesh(setup):
    """f64 particles on an f32 mesh (legal under x64) must walk the packed
    body: the topology bitcast width follows the TABLE dtype, not the
    particle dtype."""
    mesh, _mesh_unpacked, args, kw, base = setup
    args64 = (
        mesh,
        args[1].astype(jnp.float64),
        args[2].astype(jnp.float64),
        *args[3:],
    )
    got = trace_impl(
        *args64, make_flux(mesh.ntet, 2, jnp.float64), **kw
    )
    assert bool(np.asarray(got.done).all())
    np.testing.assert_array_equal(
        np.asarray(got.material_id), np.asarray(base.material_id)
    )
    np.testing.assert_allclose(
        np.asarray(got.flux), np.asarray(base.flux), rtol=1e-5, atol=1e-6
    )


def test_packing_limits():
    """Packing-boundary behavior (round-2 test debt, VERDICT item 3b):
    exactly 64 distinct class ids still packs, 65 falls back; the 2^24
    element guard holds at the boundary."""
    # Largest stored code is neighbor_id + 1 = ntet, so ntet = 2^24 - 1
    # (code 0xFFFFFF) still fits the 24-bit field; 2^24 does not.
    assert can_pack_walk_tables((1 << 24) - 1, 64, 4)
    assert not can_pack_walk_tables(1 << 24, 64, 4)
    assert can_pack_walk_tables(1000, 64, 8)
    assert not can_pack_walk_tables(1000, 65, 8)
    assert not can_pack_walk_tables(1000, 8, 2)  # bf16 mesh can't bitcast


def test_exactly_64_classes_packs_and_matches():
    """A mesh with exactly 64 distinct class ids (the packing maximum) must
    still pack AND walk identically to its unpacked twin — class indices
    occupy the full 6-bit field."""
    coords, tets = build_box_arrays(1.0, 1.0, 1.0, 4, 4, 4)
    ntet = tets.shape[0]
    assert ntet >= 64
    rng = np.random.default_rng(7)
    # Spread ids so values need the whole 6-bit index range and are
    # non-contiguous (indices != values).
    values = np.sort(rng.choice(10_000, size=64, replace=False)).astype(
        np.int32
    )
    cid = values[np.arange(ntet) % 64]
    mesh = TetMesh.from_numpy(coords, tets, cid, dtype=jnp.float32)
    assert mesh.geo20 is not None
    mesh_u = TetMesh.from_numpy(
        coords, tets, cid, dtype=jnp.float32, packed=False
    )
    args = _particles(mesh, n=64, seed=3)
    kw = dict(initial=False, max_crossings=ntet + 8, tolerance=1e-6)
    base = trace_impl(*args, make_flux(ntet, 2, jnp.float32), **kw)
    got = trace_impl(
        mesh_u, *args[1:], make_flux(ntet, 2, jnp.float32), **kw
    )
    _assert_same(got, base, flux_exact=True)
    # With 65 classes the packed table must be refused.
    cid65 = cid.copy()
    cid65[0] = 10_001
    mesh65 = TetMesh.from_numpy(coords, tets, cid65, dtype=jnp.float32)
    assert mesh65.geo20 is None


def test_64_group_flat_keys(setup):
    """64 energy groups (the config-4 stress shape): the flat interleaved
    tally keys (elem*G + group)*2 must land every contribution in its own
    bin — pinned by comparing against a per-group sequence of 1-group
    walks."""
    mesh, _mesh_unpacked, args, kw, _base = setup
    n = args[1].shape[0]
    rng = np.random.default_rng(9)
    groups = jnp.asarray(rng.integers(0, 64, n).astype(np.int32))
    args64 = args[:6] + (groups,) + args[7:]
    got = trace_impl(
        *args64, make_flux(mesh.ntet, 64, jnp.float32), **kw
    )
    flux = np.asarray(got.flux)
    # Each particle's group gets its flux; other groups stay zero.
    used = np.unique(np.asarray(groups))
    unused = np.setdiff1d(np.arange(64), used)
    assert not flux[:, unused, :].any()
    # Group-summed flux must equal a group-blind walk of the same batch.
    blind = trace_impl(
        *args, make_flux(mesh.ntet, 2, jnp.float32), **kw
    )
    np.testing.assert_allclose(
        flux[..., 0].sum(axis=1),
        np.asarray(blind.flux)[..., 0].sum(axis=1),
        rtol=1e-6, atol=1e-6,
    )


def test_resolve_tally_scatter_uses_array_device():
    """ADVICE r4: 'auto' must resolve per call against the array that
    will run the walk, outside the jit cache key — the literal string
    frozen at first trace mispicks when backends differ."""
    from pumiumtally_tpu.ops.walk import resolve_tally_scatter

    assert resolve_tally_scatter("pair") == "pair"
    assert resolve_tally_scatter("interleaved") == "interleaved"
    # Explicit platform overrides everything.
    assert resolve_tally_scatter("auto", platform="tpu") == "interleaved"
    assert resolve_tally_scatter("auto", platform="cpu") == "pair"
    # The ARRAY's device wins over the default backend: a stub whose
    # devices() reports a TPU platform must resolve to interleaved even
    # though this process's default backend is CPU — this is the
    # regression the fix exists for (the old code always consulted
    # jax.default_backend()).
    class _TpuDev:
        platform = "tpu"

    class _TpuArray:
        def devices(self):
            return {_TpuDev()}

    assert resolve_tally_scatter("auto", _TpuArray()) == "interleaved"
    # A JAX CPU array resolves to the CPU choice.
    assert resolve_tally_scatter("auto", jnp.zeros(4)) == "pair"
    # numpy input falls back to the default backend (CPU here).
    assert resolve_tally_scatter("auto", np.zeros(4)) == "pair"
