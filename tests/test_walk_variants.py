"""The walk's performance variants (unroll, packed gathers, fused scatter)
must be bit-equivalent to the baseline flat loop — they change scheduling
and op shapes, never semantics."""
from __future__ import annotations

import numpy as np
import pytest

import jax.numpy as jnp

from pumiumtally_tpu import make_flux
from pumiumtally_tpu.mesh.box import build_box_arrays
from pumiumtally_tpu.mesh.core import TetMesh
from pumiumtally_tpu.ops.walk import trace_impl


@pytest.fixture(scope="module")
def setup():
    coords, tets = build_box_arrays(1.0, 1.0, 1.0, 4, 4, 4)
    cid = (coords[tets].mean(axis=1)[:, 0] > 0.5).astype(np.int32)
    mesh = TetMesh.from_numpy(coords, tets, cid, pack_tables=True)
    rng = np.random.default_rng(0)
    n = 96
    elem = jnp.asarray(rng.integers(0, mesh.ntet, n).astype(np.int32))
    origin = jnp.asarray(
        np.asarray(mesh.centroids())[np.asarray(elem)], jnp.float32
    )
    dest = jnp.asarray(rng.uniform(-0.1, 1.1, (n, 3)), jnp.float32)
    args = (
        mesh, origin, dest, elem,
        jnp.ones(n, bool),
        jnp.asarray(rng.uniform(0.5, 2.0, n), jnp.float32),
        jnp.asarray(rng.integers(0, 2, n), jnp.int32),
        jnp.full(n, -1, jnp.int32),
    )
    kw = dict(initial=False, max_crossings=mesh.ntet + 8, tolerance=1e-6)
    base = trace_impl(*args, make_flux(mesh.ntet, 2, jnp.float32), **kw)
    return mesh, args, kw, base


@pytest.mark.parametrize(
    "variant",
    [
        dict(unroll=4),
        dict(packed_gathers=True),
        dict(fused_scatter=True),
        dict(unroll=8, packed_gathers=True, fused_scatter=True,
             compact_after=4, compact_size=32),
        dict(compact_stages=((4, 64), (8, 48), (16, 24)), unroll=2),
    ],
    ids=["unroll", "packed", "fused", "all", "stages"],
)
def test_variant_matches_baseline(setup, variant):
    mesh, args, kw, base = setup
    got = trace_impl(
        *args, make_flux(mesh.ntet, 2, jnp.float32), **kw, **variant
    )
    np.testing.assert_allclose(
        np.asarray(got.flux), np.asarray(base.flux), atol=1e-5, rtol=1e-5
    )
    np.testing.assert_array_equal(np.asarray(got.elem), np.asarray(base.elem))
    np.testing.assert_array_equal(
        np.asarray(got.material_id), np.asarray(base.material_id)
    )
    np.testing.assert_allclose(
        np.asarray(got.position), np.asarray(base.position), atol=1e-6
    )
    assert int(got.n_segments) == int(base.n_segments)
    assert bool(np.asarray(got.done).all())
