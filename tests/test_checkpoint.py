"""Checkpoint/resume round trip: a run interrupted and resumed must produce
bit-identical tallies and particle state to an uninterrupted run."""
from __future__ import annotations

import numpy as np
import pytest

from pumiumtally_tpu import PumiTally, TallyConfig, build_box


def _drive(tally, moves, seed):
    rng = np.random.default_rng(seed)
    n = tally.num_particles
    for _ in range(moves):
        dest = rng.uniform(0.05, 0.95, (n, 3))
        flying = np.ones(n, np.int8)
        weights = rng.uniform(0.5, 2.0, n)
        groups = rng.integers(0, tally.config.n_groups, n).astype(np.int32)
        mats = np.full(n, -1, np.int32)
        tally.move_to_next_location(dest, flying, weights, groups, mats)


def _fresh(n=16):
    mesh = build_box(1.0, 1.0, 1.0, 3, 3, 3)
    t = PumiTally(mesh, n, TallyConfig(tolerance=1e-6))
    rng = np.random.default_rng(42)
    t.initialize_particle_location(rng.uniform(0.1, 0.9, (n, 3)).ravel())
    return t


def test_round_trip_matches_uninterrupted(tmp_path):
    ckpt = str(tmp_path / "tally.npz")

    a = _fresh()
    _drive(a, 3, seed=1)
    a.save_checkpoint(ckpt)
    _drive(a, 2, seed=2)

    b = _fresh()
    b.restore_checkpoint(ckpt)
    assert b.iter_count == 3
    _drive(b, 2, seed=2)

    np.testing.assert_array_equal(a.raw_flux, b.raw_flux)
    np.testing.assert_array_equal(a.element_ids, b.element_ids)
    np.testing.assert_array_equal(
        np.asarray(a.state.origin), np.asarray(b.state.origin)
    )
    assert a.total_segments == b.total_segments


def test_adaptive_replan_state_rides_checkpoints(tmp_path):
    """compact_stages='adaptive' replans the compaction ladder from the
    FIRST move's measured stats; a resumed run must reuse that ladder
    (not replan from a post-resume move) or the scatter grouping — and
    thus the flux, to ~1e-15 — drifts from the uninterrupted run."""
    ckpt = str(tmp_path / "tally.npz")
    mesh = build_box(1.0, 1.0, 1.0, 3, 3, 3)
    n = 1024
    cfg = TallyConfig(tolerance=1e-6, compact_stages="adaptive")

    def fresh():
        t = PumiTally(mesh, n, cfg)
        rng = np.random.default_rng(7)
        t.initialize_particle_location(
            rng.uniform(0.1, 0.9, (n, 3)).ravel()
        )
        return t

    a = fresh()
    _drive(a, 1, seed=11)
    assert a._replanned
    a.save_checkpoint(ckpt)

    b = fresh()
    b.restore_checkpoint(ckpt)
    assert b._replanned
    assert b._compact_stages == a._compact_stages

    _drive(a, 1, seed=12)
    _drive(b, 1, seed=12)
    np.testing.assert_array_equal(a.raw_flux, b.raw_flux)


def test_mesh_mismatch_rejected(tmp_path):
    ckpt = str(tmp_path / "tally.npz")
    a = _fresh()
    a.save_checkpoint(ckpt)
    other = PumiTally(
        build_box(1.0, 1.0, 1.0, 2, 2, 2), a.num_particles,
        TallyConfig(tolerance=1e-6),
    )
    with pytest.raises(ValueError, match="different mesh"):
        other.restore_checkpoint(ckpt)


def test_shape_mismatches_rejected(tmp_path):
    ckpt = str(tmp_path / "tally.npz")
    a = _fresh()
    a.save_checkpoint(ckpt)

    mesh = build_box(1.0, 1.0, 1.0, 3, 3, 3)
    wrong_n = PumiTally(mesh, 8, TallyConfig(tolerance=1e-6))
    with pytest.raises(ValueError, match="particles"):
        wrong_n.restore_checkpoint(ckpt)

    wrong_g = PumiTally(
        mesh, a.num_particles, TallyConfig(tolerance=1e-6, n_groups=5)
    )
    with pytest.raises(ValueError, match="energy groups"):
        wrong_g.restore_checkpoint(ckpt)
