"""Test harness setup: force the CPU platform with 8 virtual devices (the
multi-chip sharding tests run on a fake mesh, SURVEY.md §5 distributed notes)
and enable float64 so the reference's 1e-8 analytic oracles port literally
(test_pumi_tally_impl_methods.cpp:22)."""
import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    )

import jax

# The environment may pin JAX_PLATFORMS to a TPU plugin in a way that wins
# over the env var set above; the config update takes final precedence.
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

# Persistent compile cache: the suite's cost is dominated by XLA compiles
# of the walk programs (one per static-config signature). Caching them on
# disk makes every re-run after the first (the common case: the driver's
# per-round gate, local red-green loops) skip the compiles entirely.
# Threshold 0 caches even sub-second entries — hit rate matters more than
# per-entry size here, and the cache lives in gitignored scratch.
jax.config.update(
    "jax_compilation_cache_dir",
    os.path.join(os.path.dirname(__file__), os.pardir, ".jax_cache_tests"),
)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
