"""In-kernel checkify invariants (OMEGA_H_CHECK_PRINTF parity).

A healthy walk must pass all device assertions; a corrupted input (NaN
destination) must trip them with a readable error instead of silently
tallying garbage."""
from __future__ import annotations

import numpy as np
import pytest

import jax.numpy as jnp

from pumiumtally_tpu import build_box, make_flux
from pumiumtally_tpu.ops.walk import checked_trace


def _args(mesh, dest):
    rng = np.random.default_rng(0)
    n = dest.shape[0]
    elem = jnp.asarray(rng.integers(0, mesh.ntet, n).astype(np.int32))
    origin = jnp.asarray(
        np.asarray(mesh.centroids())[np.asarray(elem)], jnp.float32
    )
    return (
        mesh, origin, jnp.asarray(dest, jnp.float32), elem,
        jnp.ones(n, bool), jnp.ones(n, jnp.float32),
        jnp.zeros(n, jnp.int32), jnp.full(n, -1, jnp.int32),
        make_flux(mesh.ntet, 1, jnp.float32),
    )


def test_clean_walk_passes_checks():
    mesh = build_box(1.0, 1.0, 1.0, 3, 3, 3)
    dest = np.random.default_rng(1).uniform(0.1, 0.9, (32, 3))
    err, result = checked_trace(
        *_args(mesh, dest), initial=False,
        max_crossings=mesh.ntet + 8, tolerance=1e-6,
    )
    err.throw()  # no violation
    assert float(result.flux[..., 0].sum()) > 0


def test_nan_destination_trips_check():
    mesh = build_box(1.0, 1.0, 1.0, 3, 3, 3)
    dest = np.random.default_rng(1).uniform(0.1, 0.9, (32, 3))
    dest[5] = np.nan
    err, _ = checked_trace(
        *_args(mesh, dest), initial=False,
        max_crossings=mesh.ntet + 8, tolerance=1e-6,
    )
    with pytest.raises(Exception, match="non-finite|contribution"):
        err.throw()


def test_wrong_parent_element_trips_consistency_check():
    """The walk-consistency assert (the reference's tracklength print
    analog, cpp:618-629) must fire when a particle's claimed parent
    element does not contain its position."""
    mesh = build_box(1.0, 1.0, 1.0, 3, 3, 3)
    rng = np.random.default_rng(2)
    n = 8
    elem = jnp.asarray(rng.integers(0, mesh.ntet, n).astype(np.int32))
    cents = np.asarray(mesh.centroids())
    origin = np.asarray(cents)[np.asarray(elem)]
    # Corrupt one parent id: the particle sits at elem[0]'s centroid but
    # claims the element farthest from it.
    far = int(
        np.argmax(np.linalg.norm(cents - origin[0], axis=1))
    )
    elem = elem.at[0].set(far)
    dest = rng.uniform(0.1, 0.9, (n, 3))
    err, _ = checked_trace(
        mesh,
        jnp.asarray(origin, jnp.float32),
        jnp.asarray(dest, jnp.float32),
        elem,
        jnp.ones(n, bool), jnp.ones(n, jnp.float32),
        jnp.zeros(n, jnp.int32), jnp.full(n, -1, jnp.int32),
        make_flux(mesh.ntet, 1, jnp.float32),
        initial=False, max_crossings=mesh.ntet + 8, tolerance=1e-6,
    )
    with pytest.raises(Exception, match="outside its parent element"):
        err.throw()
