"""Megastep: K device-sourced moves fused into one compiled program.

The structural contracts, pinned so the fusion cannot silently rot:

  * BITWISE IDENTITY — ``run_source_moves`` with megastep=K produces
    bit-identical flux, particle state and counters to K per-dispatch
    (megastep=1) moves, on both facades, across dtypes and io_pipeline
    modes (the RNG streams are keyed by (seed, move, particle id), so
    fusion is pure control flow).
  * TRANSFER COUNT — a steady-state megastep issues exactly ONE H2D
    (the move counter) and ONE D2H (the packed stats/integrity/
    convergence/physics tail) per K moves, under
    ``jax.transfer_guard("disallow")`` + the pumi_h2d/d2h counters.
  * FUSED TAILS — convergence (batch cadence counting device moves),
    integrity and telemetry reductions agree between the fused and
    per-dispatch loops.
  * RESUMABILITY — checkpoint restore mid-batch continues the RNG
    stream and slot layout bitwise; the ResilientRunner replays a
    transiently-failed megastep bitwise from its last-good snapshot.
  * NO-MUTATION — the per-move facade reads, never mutates, its
    weights/groups inputs (the models/transport.py copy-removal
    satellite).
"""
from __future__ import annotations

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from pumiumtally_tpu import PumiTally, TallyConfig
from pumiumtally_tpu.mesh.box import build_box_arrays
from pumiumtally_tpu.mesh.core import TetMesh
from pumiumtally_tpu.models.transport import Material, SyntheticTransport
from pumiumtally_tpu.ops.source import SourceParams
from pumiumtally_tpu.parallel.partitioned_api import PartitionedTally

N = 64
MOVES = 4

SRC = SourceParams(
    sigma_t={1: 4.0, 2: 9.0},
    absorption={1: 0.3, 2: 0.5},
    survival_weight=0.2,
    seed=13,
)


def _jittered_two_region(nx=4, jitter=0.2, seed=11, dtype=jnp.float64):
    coords, t2v = build_box_arrays(1.0, 1.0, 1.0, nx, nx, nx)
    rng = np.random.default_rng(seed)
    h = 1.0 / nx
    interior = np.all((coords > 1e-9) & (coords < 1 - 1e-9), axis=1)
    coords = coords.copy()
    coords[interior] += rng.uniform(
        -jitter * h, jitter * h, (int(interior.sum()), 3)
    )
    cen = coords[t2v].mean(axis=1)
    cls = np.where(cen[:, 0] < 0.5, 1, 2).astype(np.int32)
    return TetMesh.from_numpy(coords, t2v, class_id=cls, dtype=dtype)


@pytest.fixture(scope="module")
def mesh64():
    return _jittered_two_region(dtype=jnp.float64)


@pytest.fixture(scope="module")
def mesh32():
    return _jittered_two_region(dtype=jnp.float32)


def _init(t, seed=3):
    pos = np.random.default_rng(seed).uniform(0.1, 0.9, (N, 3))
    t.initialize_particle_location(pos.ravel().copy())


def _single_state(t):
    s = t.state
    return {
        "flux": t.raw_flux,
        "origin": np.asarray(s.origin),
        "elem": np.asarray(s.elem),
        "material_id": np.asarray(s.material_id),
        "weight": np.asarray(s.weight),
        "group": np.asarray(s.group),
        "alive": np.asarray(s.in_flight),
    }


def _assert_out_equal(oa, ob):
    for f in ("moves", "segments", "collisions", "escaped", "rouletted",
              "alive", "truncated"):
        assert oa[f] == ob[f], f
    # absorbed_weight is an fp accumulation whose grouping legitimately
    # differs across chunkings (device partial sums vs host refolds).
    assert np.isclose(
        oa["absorbed_weight"], ob["absorbed_weight"], rtol=1e-5
    )


# --------------------------------------------------------------------- #
# Bitwise identity: megastep-K vs K per-dispatch moves
# --------------------------------------------------------------------- #
# The legacy-mode variants compile a fresh per-move reference program
# per dtype and dominate this suite's wall time; they stay in the full
# suite (the tier1.yml megastep step runs this file unfiltered) but are
# excluded from the fast core run to protect its time budget.
@pytest.mark.parametrize("io", [
    pytest.param("legacy", marks=pytest.mark.slow),
    "packed",
    "overlap",
])
@pytest.mark.parametrize("dtype", ["float32", "float64"])
def test_single_chip_megastep_bitwise(mesh32, mesh64, dtype, io):
    mesh = mesh64 if dtype == "float64" else mesh32
    w0 = np.random.default_rng(5).uniform(0.5, 2.0, N)
    g0 = np.random.default_rng(6).integers(0, 2, N).astype(np.int32)

    def run(K):
        t = PumiTally(
            mesh, N,
            TallyConfig(
                n_groups=2, dtype=jnp.dtype(dtype), tolerance=1e-6,
                io_pipeline=io, megastep=K,
            ),
        )
        _init(t)
        out = t.run_source_moves(MOVES, SRC, weights=w0, groups=g0)
        return t, out

    a, oa = run(1)
    b, ob = run(2)  # 4 moves = two fused chunks of 2
    _assert_out_equal(oa, ob)
    sa, sb = _single_state(a), _single_state(b)
    for name in sa:
        np.testing.assert_array_equal(sb[name], sa[name], err_msg=name)
    assert a.total_segments == b.total_segments
    assert a.iter_count == b.iter_count == MOVES


def test_consecutive_calls_chain_bitwise(mesh64):
    """run_source_moves(2) twice == run_source_moves(4) once: the alive
    flag and RNG stream persist in device state between calls."""
    def mk():
        t = PumiTally(
            mesh64, N,
            TallyConfig(
                n_groups=2, dtype=jnp.float64, tolerance=1e-8,
                megastep=2,
            ),
        )
        _init(t)
        return t

    a = mk()
    a.run_source_moves(4, SRC, weights=np.ones(N))
    b = mk()
    b.run_source_moves(2, SRC, weights=np.ones(N))
    b.run_source_moves(2, SRC)
    sa, sb = _single_state(a), _single_state(b)
    for name in sa:
        np.testing.assert_array_equal(sb[name], sa[name], err_msg=name)


def test_partitioned_megastep_bitwise_and_transfers(mesh64):
    w0 = np.ones(N)
    g0 = np.zeros(N, np.int32)

    def run(K):
        t = PartitionedTally(
            mesh64, N,
            TallyConfig(
                n_groups=2, dtype=jnp.float64, tolerance=1e-8,
                megastep=K,
            ),
            n_parts=4, halo_layers=1,
        )
        _init(t)
        out = t.run_source_moves(3, SRC, weights=w0, groups=g0)
        return t, out

    a, oa = run(1)
    b, ob = run(3)
    _assert_out_equal(oa, ob)
    np.testing.assert_array_equal(b.raw_flux, a.raw_flux)
    a._sync_source_state()
    b._sync_source_state()
    for name in ("positions", "elem_global", "material_id", "weights",
                 "groups", "alive"):
        np.testing.assert_array_equal(
            getattr(b, name), getattr(a, name), err_msg=name
        )
    assert a.total_segments == b.total_segments

    # Steady-state transfer invariant on the fused loop: continuing b
    # (state device-resident, program compiled) costs exactly one H2D —
    # the move counter — and one D2H — the packed tail — for 3 moves.
    tot0 = b.telemetry()["totals"]
    with jax.transfer_guard("disallow"):
        b.run_source_moves(3, SRC)
    tot1 = b.telemetry()["totals"]
    assert tot1["h2d_transfers"] - tot0["h2d_transfers"] == 1
    assert tot1["d2h_transfers"] - tot0["d2h_transfers"] == 1
    assert tot1["moves"] - tot0["moves"] == 3


# --------------------------------------------------------------------- #
# Transfer invariant with every fused tail on (single chip)
# --------------------------------------------------------------------- #
def test_single_chip_megastep_transfer_invariant(mesh64):
    t = PumiTally(
        mesh64, N,
        TallyConfig(
            n_groups=2, dtype=jnp.float64, tolerance=1e-8, megastep=2,
            convergence=True, batch_moves=2, integrity="warn",
        ),
    )
    _init(t)
    t.run_source_moves(2, SRC, weights=np.ones(N))  # warm/compile
    tot0 = t.telemetry()["totals"]
    with jax.transfer_guard("disallow"):
        t.run_source_moves(2, SRC)
    tot1 = t.telemetry()["totals"]
    assert tot1["h2d_transfers"] - tot0["h2d_transfers"] == 1
    assert tot1["d2h_transfers"] - tot0["d2h_transfers"] == 1
    assert tot1["moves"] - tot0["moves"] == 2
    assert tot1["segments"] > tot0["segments"]
    # Clean physics must not trip the integrity escalation.
    viol = t.telemetry()["integrity"]["violations"]
    assert all(v == 0 for v in viol.values()), viol


# --------------------------------------------------------------------- #
# Fused-tail parity: convergence / integrity / telemetry
# --------------------------------------------------------------------- #
def test_megastep_convergence_parity(mesh64):
    def run(K):
        t = PumiTally(
            mesh64, N,
            TallyConfig(
                n_groups=2, dtype=jnp.float64, tolerance=1e-8,
                megastep=K, convergence=True, batch_moves=2,
            ),
        )
        _init(t)
        t.run_source_moves(MOVES, SRC, weights=np.ones(N))
        return t

    a, b = run(1), run(4)
    ca = a.telemetry()["convergence"]
    cb = b.telemetry()["convergence"]
    # The batch cadence counts DEVICE moves: 4 moves / batch_moves=2
    # gives 2 closed batches either way, and the final statistics agree
    # (the accumulators fold inside the program, move by move).
    assert ca["n_batches"] == cb["n_batches"] == 2
    for f in ("scored", "rel_err_mean", "rel_err_max",
              "converged_fraction"):
        assert ca[f] == cb[f], f
    np.testing.assert_array_equal(
        a.relative_error(), b.relative_error()
    )


def test_megastep_telemetry_records(mesh64):
    t = PumiTally(
        mesh64, N,
        TallyConfig(
            n_groups=2, dtype=jnp.float64, tolerance=1e-8, megastep=2,
        ),
    )
    _init(t)
    out = t.run_source_moves(MOVES, SRC, weights=np.ones(N))
    tm = t.telemetry()
    recs = [r for r in tm["per_move"] if r["kind"] == "megastep"]
    assert len(recs) == 2  # 4 moves in two fused chunks
    assert all(r["moves"] == 2 for r in recs)
    assert tm["totals"]["moves"] == MOVES
    assert tm["totals"]["segments"] == out["segments"]
    assert sum(r["collisions"] for r in recs) == out["collisions"]


# --------------------------------------------------------------------- #
# Checkpoint restore mid-batch
# --------------------------------------------------------------------- #
def test_single_chip_megastep_checkpoint_restore(mesh64, tmp_path):
    cfg = TallyConfig(
        n_groups=2, dtype=jnp.float64, tolerance=1e-8, megastep=3,
    )
    a = PumiTally(mesh64, N, cfg)
    _init(a)
    a.run_source_moves(3, SRC, weights=np.ones(N))
    ck = str(tmp_path / "mega.npz")
    a.save_checkpoint(ck)
    a.run_source_moves(3, SRC)

    b = PumiTally(mesh64, N, cfg)
    b.restore_checkpoint(ck)
    b.run_source_moves(3, SRC)
    sa, sb = _single_state(a), _single_state(b)
    for name in sa:
        np.testing.assert_array_equal(sb[name], sa[name], err_msg=name)
    assert a.iter_count == b.iter_count == 6


def test_partitioned_megastep_checkpoint_restore(mesh64, tmp_path):
    cfg = dict(n_groups=2, dtype=jnp.float64, tolerance=1e-8, megastep=2)
    a = PartitionedTally(
        mesh64, N, TallyConfig(**cfg), n_parts=4, halo_layers=1
    )
    _init(a)
    a.run_source_moves(2, SRC, weights=np.ones(N))
    ck = str(tmp_path / "mega_part.npz")
    a.save_checkpoint(ck)
    a.run_source_moves(2, SRC)

    b = PartitionedTally(
        mesh64, N, TallyConfig(**cfg), n_parts=4, halo_layers=1
    )
    b.restore_checkpoint(ck)
    b.run_source_moves(2, SRC)
    # Same partition layout ⇒ the persisted slot state resumes the run
    # bitwise, flux summation order included.
    np.testing.assert_array_equal(b.raw_flux, a.raw_flux)
    a._sync_source_state()
    b._sync_source_state()
    for name in ("positions", "elem_global", "material_id", "weights",
                 "groups", "alive"):
        np.testing.assert_array_equal(
            getattr(b, name), getattr(a, name), err_msg=name
        )


# --------------------------------------------------------------------- #
# ResilientRunner retry replay at megastep granularity
# --------------------------------------------------------------------- #
def test_runner_megastep_transient_retry(mesh64, tmp_path):
    from pumiumtally_tpu.resilience.faultinject import (
        FaultInjector,
        FaultPlan,
    )
    from pumiumtally_tpu.resilience.runner import ResilientRunner

    pos = np.random.default_rng(3).uniform(0.1, 0.9, (N, 3)).ravel()

    def run(tag, faults=None):
        t = PumiTally(
            mesh64, N,
            TallyConfig(
                n_groups=2, dtype=jnp.float64, tolerance=1e-8,
                megastep=2,
            ),
        )
        with ResilientRunner(
            t, str(tmp_path / tag), every_moves=2,
            handle_signals=False, sleep=lambda s: None, faults=faults,
        ) as run_:
            run_.initialize_particle_location(pos.copy())
            run_.run_source_moves(2, SRC, weights=np.ones(N))
            run_.run_source_moves(2, SRC)
            run_.run_source_moves(2, SRC)
        return t

    a = run("clean")
    # The transient fires at move 3 (the second megastep); the runner
    # must roll back to the last-good snapshot and replay bitwise.
    b = run("faulty", FaultInjector(FaultPlan(transient_at_move=3)))
    sa, sb = _single_state(a), _single_state(b)
    for name in sa:
        np.testing.assert_array_equal(sb[name], sa[name], err_msg=name)
    assert b.metrics.counter(
        "pumi_move_retries_total",
        "transient move failures retried by the supervisor",
    ).value() == 1


def test_runner_megastep_midcall_checkpoint_cadence(mesh64, tmp_path):
    """ONE long run_source_moves call is supervised in megastep-K
    chunks: the every-N-moves checkpoint cadence fires BETWEEN the
    fused dispatches, bounding the preemption loss window to one
    megastep (not the whole call), and the chunked call stays bitwise
    identical to the unchunked facade loop."""
    from pumiumtally_tpu.resilience.runner import ResilientRunner

    pos = np.random.default_rng(3).uniform(0.1, 0.9, (N, 3)).ravel()
    t = PumiTally(
        mesh64, N,
        TallyConfig(
            n_groups=2, dtype=jnp.float64, tolerance=1e-8, megastep=2,
        ),
    )
    with ResilientRunner(
        t, str(tmp_path / "cadence"), every_moves=2,
        handle_signals=False, sleep=lambda s: None,
    ) as run_:
        run_.initialize_particle_location(pos.copy())
        run_.run_source_moves(6, SRC, weights=np.ones(N))
        # 6 moves = 3 chunks of K=2; cadence every 2 moves → one
        # checkpoint per chunk boundary, written DURING the call.
        assert run_.store.find_latest() is not None
        assert t.iter_count == 6
        assert (
            t.metrics.counter(
                "pumi_checkpoints_total",
                "checkpoint generations written by the supervisor",
            ).value() >= 3
        )

    ref = PumiTally(
        mesh64, N,
        TallyConfig(
            n_groups=2, dtype=jnp.float64, tolerance=1e-8, megastep=2,
        ),
    )
    ref.initialize_particle_location(pos.copy())
    ref.run_source_moves(6, SRC, weights=np.ones(N))
    sa, sb = _single_state(t), _single_state(ref)
    for name in sa:
        np.testing.assert_array_equal(sa[name], sb[name], err_msg=name)


# --------------------------------------------------------------------- #
# Knob semantics + facade-input no-mutation + driver modes
# --------------------------------------------------------------------- #
def test_resolve_megastep_knob(monkeypatch):
    assert TallyConfig().resolve_megastep() == 1
    assert TallyConfig(megastep=4).resolve_megastep() == 4
    monkeypatch.setenv("PUMI_TPU_MEGASTEP", "8")
    assert TallyConfig(megastep=4).resolve_megastep() == 8
    monkeypatch.delenv("PUMI_TPU_MEGASTEP")
    with pytest.raises(ValueError, match="megastep"):
        TallyConfig(megastep=0).resolve_megastep()


@pytest.mark.parametrize("io", ["packed", "legacy"])
def test_move_inputs_never_mutated(mesh64, io):
    """The per-move facade READS weights/groups, never writes them —
    the contract that lets models/transport.py drop its per-event
    defensive copies."""
    t = PumiTally(
        mesh64, 32,
        TallyConfig(
            n_groups=2, dtype=jnp.float64, tolerance=1e-8,
            io_pipeline=io,
        ),
    )
    rng = np.random.default_rng(0)
    t.initialize_particle_location(
        rng.uniform(0.1, 0.9, (32, 3)).ravel()
    )
    w = rng.uniform(0.5, 2.0, 32)
    g = rng.integers(0, 2, 32).astype(np.int32)
    w0, g0 = w.copy(), g.copy()
    t.move_to_next_location(
        rng.uniform(0.1, 0.9, (32, 3)), np.ones(32, np.int8), w, g,
        np.full(32, -1, np.int32),
    )
    np.testing.assert_array_equal(w, w0)
    np.testing.assert_array_equal(g, g0)


def test_transport_megastep_default(mesh64):
    """SyntheticTransport defaults to the device-sourced fused loop and
    still produces a physically coherent batch (every outcome class on
    a two-region mesh)."""
    t = PumiTally(
        mesh64, 48,
        TallyConfig(n_groups=2, dtype=jnp.float64, tolerance=1e-8),
    )
    d = SyntheticTransport(
        t,
        materials={1: Material(4.0, 0.4), 2: Material(8.0, 0.6)},
        seed=3,
        max_events=100,
    )
    assert d.mode == "megastep"
    stats = d.run(batches=1)
    assert stats.batches == 1
    assert stats.events > 0
    assert stats.collisions > 0
    assert stats.absorbed_weight > 0
    assert stats.boundary_escapes + stats.roulette_kills > 0
    flux = t.raw_flux
    cid = np.asarray(mesh64.class_id)
    assert flux[cid == 1, :, 0].sum() > 0
    assert flux[cid == 2, :, 0].sum() > 0
    assert flux[:, 1, 0].sum() > 0  # downscatter populated group 1


def test_partitioned_restage_continues_from_device_state(mesh64):
    """Re-staging SOME physics lanes mid-run must not rewind the rest:
    positions/elements (and every omitted lane) continue from live
    device state, exactly like PumiTally._stage_source_lanes — NOT from
    the host mirrors, which are stale between read surfaces."""
    def mk():
        t = PartitionedTally(
            mesh64, N,
            TallyConfig(
                n_groups=2, dtype=jnp.float64, tolerance=1e-8,
                megastep=2,
            ),
            n_parts=4, halo_layers=1,
        )
        _init(t)
        return t

    w1 = np.random.default_rng(9).uniform(0.5, 2.0, N)
    a = mk()
    pos0 = a.positions.copy()
    a.run_source_moves(2, SRC)
    a.run_source_moves(2, SRC, weights=w1)  # implicit mid-run re-stage

    b = mk()
    b.run_source_moves(2, SRC)
    b._sync_source_state()  # oracle: explicit fold-back before re-stage
    b.run_source_moves(2, SRC, weights=w1)

    a._sync_source_state()
    b._sync_source_state()
    # The first call really moved particles, so a rewind would diverge.
    assert not np.array_equal(a.positions, pos0)
    np.testing.assert_array_equal(a.raw_flux, b.raw_flux)
    for name in ("positions", "elem_global", "material_id", "weights",
                 "groups", "alive"):
        np.testing.assert_array_equal(
            getattr(a, name), getattr(b, name), err_msg=name
        )


def test_pipeline_drain_all_done_requires_dead(mesh64):
    """BatchResult.all_done on a submit_source() batch means the whole
    event loop FINISHED: particles still alive when n_moves ran out are
    unfinished work, not a clean batch."""
    from pumiumtally_tpu.models.pipeline import StreamingTallyPipeline

    def run(n_moves):
        pipe = StreamingTallyPipeline(
            mesh64,
            TallyConfig(n_groups=2, dtype=jnp.float64, tolerance=1e-8),
            depth=1,
        )
        cents = np.asarray(mesh64.centroids())
        e = np.random.default_rng(0).integers(
            0, mesh64.ntet, N
        ).astype(np.int32)
        pipe.submit_source(
            cents[e], e, n_moves,
            SourceParams(sigma_t={1: 5.0, 2: 5.0}, seed=1),
        )
        pipe.finish()
        return list(pipe.results())[0]

    short = run(1)  # one move cannot terminate every particle
    assert short.physics["alive"] > 0
    assert not short.all_done
    full = run(200)
    assert full.physics["alive"] == 0
    assert full.all_done == (full.physics["truncated"] == 0)


def test_pipeline_submit_source(mesh64):
    from pumiumtally_tpu.models.pipeline import StreamingTallyPipeline

    pipe = StreamingTallyPipeline(
        mesh64,
        TallyConfig(n_groups=2, dtype=jnp.float64, tolerance=1e-8),
        depth=2,
    )
    cents = np.asarray(mesh64.centroids())
    rng = np.random.default_rng(0)
    for i in range(2):
        e = rng.integers(0, mesh64.ntet, N).astype(np.int32)
        pipe.submit_source(
            cents[e], e, 3,
            SourceParams(sigma_t={1: 5.0, 2: 5.0}, seed=i),
        )
    flux = pipe.finish()
    assert flux[..., 0].sum() > 0
    rs = list(pipe.results())
    assert len(rs) == 2
    for r in rs:
        assert r.physics is not None
        assert r.physics["collisions"] >= 0
        assert r.n_segments > 0
