"""VTU output round-trip: parse the base64-encoded XML we write and check
coordinates, connectivity, and per-cell fields come back bit-exact."""
from __future__ import annotations

import base64
import re
import struct

import numpy as np

from pumiumtally_tpu import build_box
from pumiumtally_tpu.io.vtk import write_vtu


def _parse_data_arrays(text):
    out = {}
    for m in re.finditer(
        r'<DataArray type="(\w+)" Name="([^"]+)"[^>]*format="binary">\s*'
        r"([A-Za-z0-9+/=\s]+?)\s*</DataArray>",
        text,
    ):
        vtype, name, payload = m.groups()
        raw = base64.b64decode("".join(payload.split()))
        (nbytes,) = struct.unpack("<I", raw[:4])
        body = raw[4 : 4 + nbytes]
        dtype = {
            "Float64": np.float64,
            "Float32": np.float32,
            "Int64": np.int64,
            "Int32": np.int32,
            "UInt8": np.uint8,
        }[vtype]
        out[name] = np.frombuffer(body, dtype=dtype)
    return out


def test_vtu_round_trip(tmp_path):
    mesh = build_box(1.0, 2.0, 0.5, 2, 3, 1)
    coords = np.asarray(mesh.coords, np.float64)
    tets = np.asarray(mesh.tet2vert, np.int64)
    rng = np.random.default_rng(0)
    fields = {
        "flux_group_0": rng.random(mesh.ntet),
        "volume": np.asarray(mesh.volumes, np.float64),
    }
    path = str(tmp_path / "mesh.vtu")
    write_vtu(path, coords, tets, fields)
    text = open(path).read()

    arrays = _parse_data_arrays(text)
    np.testing.assert_array_equal(
        arrays["Points"].reshape(-1, 3), coords
    )
    np.testing.assert_array_equal(
        arrays["connectivity"].reshape(-1, 4), tets
    )
    np.testing.assert_array_equal(
        arrays["offsets"], (np.arange(mesh.ntet) + 1) * 4
    )
    assert (arrays["types"] == 10).all()  # VTK_TETRA
    np.testing.assert_array_equal(arrays["flux_group_0"], fields["flux_group_0"])
    np.testing.assert_array_equal(arrays["volume"], fields["volume"])
    # Declared sizes match.
    m = re.search(r'NumberOfPoints="(\d+)" NumberOfCells="(\d+)"', text)
    assert (int(m.group(1)), int(m.group(2))) == (mesh.nverts, mesh.ntet)
