"""Cost-model layer tests (pumiumtally_tpu/analysis/costmodel.py).

The compile-time performance contracts must (a) be deterministic — the
committed PERF_CONTRACTS.json is byte-stable across fresh processes on
one environment, (b) hold on the committed capture (the baseline-free
invariants pass with no tolerance games), and (c) actually catch the
regressions they claim to: an accidental f64 upcast (flop census), a
dropped donation (peak-memory jump via the alias bound), a quadratic
broadcast (scaling exponent across the shape ladder), and a drifted
Pallas VMEM estimator — each INJECTED here and asserted to fail with
its *named* finding.  The drift diff and its per-metric tolerance bands
are unit-tested on tampered captures, and scripts/perfdiff.py's table
is smoke-tested end to end.

The in-process tests run under the pytest environment (x64 ON — which
is exactly what makes the injected f64 upcast representable); the
determinism tests spawn fresh processes that pin the canonical
cpu/8-device/x64-off lint environment like scripts/lint.py does.
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
import textwrap
from pathlib import Path

import pytest

import jax
import jax.numpy as jnp

from pumiumtally_tpu.analysis import contracts as C
from pumiumtally_tpu.analysis import costmodel as M
from pumiumtally_tpu.ops import walk

ROOT = Path(__file__).resolve().parents[1]

_N_LADDER = (16, 64, 256)


def _symbols(findings):
    return [f.symbol for f in findings]


# --------------------------------------------------------------------- #
# Helpers: compile a (possibly poisoned) wrapped walk-trace program and
# assemble a single-family capture the check functions accept.
# --------------------------------------------------------------------- #
def _wrapped_trace(n, poison=None, donate=True):
    mesh, a = C._problem(jnp.float32, n=n)
    statics = C._walk_statics()

    def wrapped(origin, dest, elem, fly, w, g, mat, flux):
        r = walk.trace_impl(
            mesh, origin, dest, elem, fly, w, g, mat, flux, **statics
        )
        if poison == "f64":
            # The accidental audit-path upcast: real f64 flops under an
            # x64-capable runtime (and a truncation warning otherwise).
            r = r._replace(
                flux=(r.flux.astype(jnp.float64) * 1.0000001).astype(
                    r.flux.dtype
                )
            )
        elif poison == "quadratic":
            # The accidental quadratic broadcast: an [n, n] outer
            # product materialized and folded into the tally.
            outer = jnp.outer(w, w)
            r = r._replace(flux=r.flux + outer.sum(axis=1).sum())
        return r

    jitted = (
        jax.jit(wrapped, donate_argnums=(7,)) if donate
        else jax.jit(wrapped)
    )
    return jitted.trace(
        a["origin"], a["dest"], a["elem"], a["in_flight"], a["weight"],
        a["group"], a["material_id"], a["flux"],
    )


def _cap_for(metrics, scaling=None, family="trace", n=16, cells=2,
             top=None):
    entry = {
        "base": M.rung_signature(
            metrics, M.family_analytic(family, n=n, cells=cells)
        ),
        "scaling": scaling or {},
    }
    if top is not None:
        top_metrics, top_n = top
        entry["top"] = M.rung_signature(
            top_metrics, M.family_analytic(family, n=top_n, cells=cells)
        )
    return {
        "environment": C.environment(),
        "ladder": {
            "n_particles": list(M.LADDER_N),
            "ntet": [6 * c**3 for c in M.LADDER_CELLS],
        },
        "families": {family: entry},
    }


# --------------------------------------------------------------------- #
# Exponent fitting
# --------------------------------------------------------------------- #
def test_fit_exponent_recovers_powers():
    sizes = [16, 64, 256]
    assert M.fit_exponent(sizes, [7 * s for s in sizes]) == pytest.approx(
        1.0
    )
    assert M.fit_exponent(sizes, [s * s for s in sizes]) == pytest.approx(
        2.0
    )
    assert M.fit_exponent(sizes, [5000] * 3) == pytest.approx(0.0)


def test_fit_exponent_rejects_degenerate_input():
    with pytest.raises(ValueError):
        M.fit_exponent([16], [100])
    with pytest.raises(ValueError):
        M.fit_exponent([16, 64], [0, 100])


# --------------------------------------------------------------------- #
# The committed capture: invariants hold, the diff is clean vs itself
# --------------------------------------------------------------------- #
def test_committed_perf_contracts_satisfy_invariants():
    cap = M.load_perf_contracts(ROOT / "PERF_CONTRACTS.json")
    assert M.check_cost(cap) == [], _symbols(M.check_cost(cap))
    assert M.diff_cost(cap, json.loads(json.dumps(cap))) == []


def test_committed_capture_carries_both_rungs():
    """Every family records the base AND the top n_particles rung —
    the top rung is where the analytic memory terms dominate the fixed
    slack, making the peak gate meaningful."""
    cap = M.load_perf_contracts(ROOT / "PERF_CONTRACTS.json")
    for fam, entry in cap["families"].items():
        assert set(entry) >= {"base", "top", "scaling"}, fam
        assert entry["base"]["analytic"]["n"] == M.LADDER_N[0]
        assert entry["top"]["analytic"]["n"] == M.LADDER_N[-1]


def test_committed_scaling_exponents_are_linear_or_better():
    cap = M.load_perf_contracts(ROOT / "PERF_CONTRACTS.json")
    for fam, entry in cap["families"].items():
        for axis, exps in entry["scaling"].items():
            for metric, e in exps.items():
                assert e <= 1.1, (
                    f"{fam}.{axis}.{metric} exponent {e} — the clean "
                    "programs are supposed to be (sub)linear"
                )


# --------------------------------------------------------------------- #
# Injected regression: accidental f64 upcast -> flop census
# --------------------------------------------------------------------- #
def test_injected_f64_upcast_names_cost_f64():
    clean = M.compile_metrics(_wrapped_trace(16))
    assert clean["f64_ops"] == 0  # the control stays pure even on x64
    poisoned = M.compile_metrics(_wrapped_trace(16, poison="f64"))
    assert poisoned["f64_ops"] > 0
    syms = _symbols(M.check_cost(_cap_for(poisoned)))
    assert "cost.f64.trace" in syms
    assert "cost.f64.trace" not in _symbols(
        M.check_cost(_cap_for(clean))
    )


# --------------------------------------------------------------------- #
# Injected regression: dropped donation -> peak-memory jump
# --------------------------------------------------------------------- #
def test_injected_dropped_donation_names_cost_donation():
    donated = M.compile_metrics(_wrapped_trace(16))
    dropped = M.compile_metrics(_wrapped_trace(16, donate=False))
    flux_bytes = M.family_analytic("trace", n=16, cells=2)["flux_bytes"]
    assert donated["alias_bytes"] >= flux_bytes
    assert dropped["alias_bytes"] < flux_bytes
    # The whole point: losing the alias IS a peak-memory jump of one
    # accumulator.
    assert dropped["peak_bytes"] >= donated["peak_bytes"] + flux_bytes
    syms = _symbols(M.check_cost(_cap_for(dropped)))
    assert "cost.donation.trace" in syms
    assert "cost.donation.trace" not in _symbols(
        M.check_cost(_cap_for(donated))
    )


# --------------------------------------------------------------------- #
# Injected regression: quadratic broadcast -> scaling exponent
# --------------------------------------------------------------------- #
def test_injected_quadratic_broadcast_names_cost_scaling():
    def ladder(poison):
        rungs = [
            M.compile_metrics(_wrapped_trace(n, poison=poison))
            for n in _N_LADDER
        ]
        exps = {
            metric: round(
                M.fit_exponent(
                    list(_N_LADDER), [r[metric] for r in rungs]
                ),
                3,
            )
            for metric in M.SCALING_METRICS
        }
        return rungs, exps

    clean_rungs, clean_exps = ladder(None)
    assert all(e <= M.SCALING_MAX["n_particles"]
               for e in clean_exps.values()), clean_exps
    quad_rungs, quad_exps = ladder("quadratic")
    # The [n, n] intermediate shows up in the memory plan even when the
    # flop fit is still masked by the linear walk term.
    assert any(e > M.SCALING_MAX["n_particles"]
               for e in quad_exps.values()), quad_exps

    cap = _cap_for(quad_rungs[0], scaling={"n_particles": quad_exps},
                   top=(quad_rungs[-1], _N_LADDER[-1]))
    findings = M.check_cost(cap)
    assert "cost.scaling.n_particles.trace" in _symbols(findings)
    offender = [f for f in findings
                if f.symbol == "cost.scaling.n_particles.trace"][0]
    assert "superlinear" in offender.message
    # At the top rung the materialized [256, 256] f32 intermediate also
    # overflows the analytic temp allowance — the peak gate catches the
    # same regression even without the ladder fit.
    assert "cost.peak.trace" in _symbols(findings)
    top_a = M.family_analytic("trace", n=_N_LADDER[-1], cells=2)
    assert quad_rungs[-1]["temp_bytes"] > M.temp_allowance_bytes(top_a)

    clean_cap = _cap_for(
        clean_rungs[0], scaling={"n_particles": clean_exps},
        top=(clean_rungs[-1], _N_LADDER[-1]),
    )
    clean_syms = _symbols(M.check_cost(clean_cap))
    assert "cost.scaling.n_particles.trace" not in clean_syms
    assert "cost.peak.trace" not in clean_syms


# --------------------------------------------------------------------- #
# Injected regression: VMEM estimator drift -> contract mirror
# --------------------------------------------------------------------- #
def test_injected_vmem_estimator_drift_names_cost_vmem(monkeypatch):
    from pumiumtally_tpu.ops import walk_pallas

    cap = M.load_perf_contracts(ROOT / "PERF_CONTRACTS.json")
    assert "cost.vmem.pallas" not in _symbols(M.check_cost(cap))

    real = walk_pallas.kernel_vmem_bytes
    monkeypatch.setattr(
        walk_pallas, "kernel_vmem_bytes",
        lambda *a, **kw: real(*a, **kw) // 2,  # "forgot half the tiles"
    )
    syms = _symbols(M.check_cost(cap))
    assert "cost.vmem.pallas" in syms


def test_vmem_estimator_matches_analytic_footprint():
    """The live estimator and the costmodel mirror agree at every rung
    of the ladder (the real gate checks the base rung; drift at any
    size would eventually migrate there)."""
    from pumiumtally_tpu.ops.walk_pallas import kernel_vmem_bytes

    for n in M.LADDER_N:
        for cells in M.LADDER_CELLS:
            ntet = 6 * cells**3
            est = kernel_vmem_bytes(ntet, n, 2, 4)
            ref = M.pallas_footprint_bytes(ntet, n, 2, 4)
            assert abs(est - ref) <= M.VMEM_TOL * ref


# --------------------------------------------------------------------- #
# Drift diff: tolerance bands and named findings
# --------------------------------------------------------------------- #
def _tampered(cap, fn):
    t = json.loads(json.dumps(cap))
    fn(t)
    return t


def test_diff_cost_names_out_of_band_drift():
    cap = M.load_perf_contracts(ROOT / "PERF_CONTRACTS.json")

    t = _tampered(cap, lambda c: c["families"]["megastep"]["base"][
        "metrics"].__setitem__("flops", int(
            cap["families"]["megastep"]["base"]["metrics"]["flops"]
            * 1.5)))
    assert "cost.drift.flops.megastep" in _symbols(M.diff_cost(t, cap))

    # Inside the band: ±1% flops is tolerated (band is 2%).
    t = _tampered(cap, lambda c: c["families"]["megastep"]["base"][
        "metrics"].__setitem__("flops", int(
            cap["families"]["megastep"]["base"]["metrics"]["flops"]
            * 1.01)))
    assert M.diff_cost(t, cap) == []

    t = _tampered(cap, lambda c: c["families"]["trace"]["scaling"][
        "n_particles"].__setitem__("flops", 1.9))
    assert "cost.drift.scaling.n_particles.flops.trace" in _symbols(
        M.diff_cost(t, cap)
    )

    t = _tampered(cap, lambda c: c["families"].pop("pallas"))
    assert "cost.family.removed.pallas" in _symbols(M.diff_cost(t, cap))
    assert "cost.family.added.pallas" in _symbols(M.diff_cost(cap, t))


def test_diff_cost_refuses_cross_environment_and_ladder():
    cap = M.load_perf_contracts(ROOT / "PERF_CONTRACTS.json")
    t = _tampered(cap, lambda c: c["environment"].__setitem__(
        "x64", not cap["environment"]["x64"]))
    assert _symbols(M.diff_cost(cap, t)) == ["cost.environment.all"]
    t = _tampered(cap, lambda c: c["ladder"].__setitem__(
        "n_particles", [16, 64]))
    assert _symbols(M.diff_cost(cap, t)) == ["cost.ladder.all"]


# --------------------------------------------------------------------- #
# Determinism: fresh processes, identical capture
# --------------------------------------------------------------------- #
_CAPTURE_SNIPPET = textwrap.dedent(
    """
    import os, sys, json
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ.pop("JAX_ENABLE_X64", None)
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8"
    )
    sys.path.insert(0, {root!r})
    from pumiumtally_tpu.analysis import costmodel as M
    cap = M.capture(families=("trace",))
    print(json.dumps(cap, sort_keys=True))
    """
)


def _fresh_env():
    env = dict(os.environ)
    for k in ("XLA_FLAGS", "JAX_ENABLE_X64", "JAX_PLATFORMS"):
        env.pop(k, None)
    return env


def test_capture_deterministic_across_fresh_processes():
    """Two cold processes on the pinned lint environment produce the
    byte-identical capture — PERF_CONTRACTS.json can be committed."""
    snippet = _CAPTURE_SNIPPET.format(root=str(ROOT))
    outs = []
    for _ in range(2):
        proc = subprocess.run(
            [sys.executable, "-c", snippet],
            capture_output=True, text=True, env=_fresh_env(),
            cwd=str(ROOT), timeout=300,
        )
        assert proc.returncode == 0, proc.stdout + proc.stderr
        outs.append(proc.stdout.strip().splitlines()[-1])
    assert outs[0] == outs[1]
    cap = json.loads(outs[0])
    assert set(cap["families"]) == {"trace"}
    assert cap["environment"]["x64"] is False


@pytest.mark.slow
def test_full_write_perf_contracts_byte_stable(tmp_path):
    """The full five-family ladder writes byte-identical
    PERF_CONTRACTS.json in two fresh scripts/lint.py processes."""
    paths = [tmp_path / f"perf{i}.json" for i in (1, 2)]
    for p in paths:
        proc = subprocess.run(
            [sys.executable, str(ROOT / "scripts" / "lint.py"),
             "--perf-only", "--write-perf-contracts",
             "--perf-contracts", str(p)],
            capture_output=True, text=True, env=_fresh_env(),
            cwd=str(ROOT), timeout=600,
        )
        assert proc.returncode == 0, proc.stdout + proc.stderr
    assert paths[0].read_bytes() == paths[1].read_bytes()


# --------------------------------------------------------------------- #
# perfdiff.py
# --------------------------------------------------------------------- #
def test_perfdiff_prints_delta_table(tmp_path):
    cap = M.load_perf_contracts(ROOT / "PERF_CONTRACTS.json")
    new = _tampered(cap, lambda c: c["families"]["megastep"]["base"][
        "metrics"].__setitem__("flops", int(
            cap["families"]["megastep"]["base"]["metrics"]["flops"]
            * 1.5)))
    p = tmp_path / "new.json"
    p.write_text(json.dumps(new))
    proc = subprocess.run(
        [sys.executable, str(ROOT / "scripts" / "perfdiff.py"),
         str(ROOT / "PERF_CONTRACTS.json"), str(p)],
        capture_output=True, text=True, timeout=60,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "megastep" in proc.stdout
    assert "flops" in proc.stdout
    assert "+50.0%" in proc.stdout
    # unchanged families do not clutter the default table
    assert "trace_packed" not in proc.stdout


def test_perfdiff_reports_no_delta(tmp_path):
    proc = subprocess.run(
        [sys.executable, str(ROOT / "scripts" / "perfdiff.py"),
         str(ROOT / "PERF_CONTRACTS.json"),
         str(ROOT / "PERF_CONTRACTS.json")],
        capture_output=True, text=True, timeout=60,
    )
    assert proc.returncode == 0
    assert "no per-family deltas" in proc.stdout


# --------------------------------------------------------------------- #
# Capture plumbing
# --------------------------------------------------------------------- #
def test_capture_reuses_base_traced():
    """The lint runner hands the contracts layer's traced programs to
    the cost layer; the base-rung metrics must be identical to a
    self-traced capture (same shapes, same programs)."""
    traced = C.build_traced(families=("trace",))
    a = M.capture(families=("trace",), base_traced=traced)
    b = M.capture(families=("trace",))
    assert a["families"]["trace"]["base"] == b["families"]["trace"][
        "base"
    ]


def test_family_analytic_partitioned_requires_max_local():
    with pytest.raises(ValueError, match="max_local"):
        M.family_analytic("partitioned", n=16, cells=2)
    a = M.family_analytic("partitioned", n=16, cells=2, max_local=6)
    assert a["flux_bytes"] == 6 * 2 * 2 * 4
