"""Two-process jax.distributed cluster over localhost TCP (CPU backend).

The reference's multi-rank path (MPI inside pumipic/Omega_h) is exercised
by running the same SPMD program in two OS processes: each process walks
its host_local_batch share of a global particle batch, then allreduce_flux
must hand every process the identical global tally — matching a
single-process run of the full batch bit-for-bit is not required across
collectives (reduction order), so equality is to 1e-10 in f64.

Skips when the CPU backend lacks multi-process collective support.
"""
from __future__ import annotations

import os
import socket
import subprocess
import sys
import textwrap

import numpy as np
import pytest

WORKER = textwrap.dedent(
    """
    import sys
    import jax
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_enable_x64", True)

    coord, pid, outdir = sys.argv[1], int(sys.argv[2]), sys.argv[3]
    from pumiumtally_tpu.parallel.multihost import init_distributed
    assert init_distributed(coord, 2, pid)
    import numpy as np
    import jax.numpy as jnp
    from pumiumtally_tpu import build_box, make_flux
    from pumiumtally_tpu.ops.walk import trace_impl
    from pumiumtally_tpu.parallel.multihost import (
        allreduce_flux, host_local_batch,
    )

    mesh = build_box(1.0, 1.0, 1.0, 3, 3, 3, dtype=jnp.float64)
    N = 64
    rng = np.random.default_rng(0)  # same seed everywhere: same batch
    elem = rng.integers(0, mesh.ntet, N).astype(np.int32)
    origin = np.asarray(mesh.centroids())[elem]
    dest = rng.uniform(0.02, 0.98, (N, 3))
    weight = rng.uniform(0.5, 2.0, N)

    start, count = host_local_batch(N)
    sl = slice(start, start + count)
    r = trace_impl(
        mesh,
        jnp.asarray(origin[sl], jnp.float64),
        jnp.asarray(dest[sl], jnp.float64),
        jnp.asarray(elem[sl]),
        jnp.ones(count, bool),
        jnp.asarray(weight[sl], jnp.float64),
        jnp.zeros(count, jnp.int32),
        jnp.full(count, -1, jnp.int32),
        make_flux(mesh.ntet, 1, jnp.float64),
        initial=False,
        max_crossings=mesh.ntet + 8,
        tolerance=1e-8,
    )
    # The collective path, DIRECTLY (no silent fallback): failure here
    # fails the worker rather than degrading to the host gather.
    from pumiumtally_tpu.parallel.multihost import _allreduce_flux_in_program
    total = _allreduce_flux_in_program(np.asarray(r.flux))
    total_host = allreduce_flux(r.flux, in_program=False)  # host fallback
    assert np.allclose(total, total_host, rtol=0, atol=1e-12), (
        "in-program all-reduce disagrees with host-gather fallback"
    )
    # Parallel VTK: each process writes its own piece; rank 0 the index
    # (the Omega_h vtk::write_parallel analog).
    from pumiumtally_tpu.core.tally import normalize_flux
    from pumiumtally_tpu.parallel.multihost import write_parallel_vtk
    norm = np.asarray(
        normalize_flux(jnp.asarray(total), mesh.volumes, N, 1)
    )
    import os
    piece = write_parallel_vtk(os.path.join(outdir, "flux"), mesh, norm)
    assert os.path.getsize(piece) > 100
    print("RESULT", pid, float(np.asarray(total)[..., 0].sum()), count)
    """
)


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_two_process_allreduce(tmp_path):
    port = _free_port()
    coord = f"127.0.0.1:{port}"
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", WORKER, coord, str(i), str(tmp_path)],
            stdout=subprocess.PIPE,
            stderr=subprocess.PIPE,
            text=True,
            cwd="/root/repo",
        )
        for i in range(2)
    ]
    outs = []
    for p in procs:
        try:
            out, err = p.communicate(timeout=240)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            pytest.skip("distributed CPU cluster timed out")
        if p.returncode != 0:
            if any(
                key in err
                for key in (
                    "not implemented",
                    "UNIMPLEMENTED",
                    "Unsupported",
                    # jax 0.4.x CPU backend phrasing: "Multiprocess
                    # computations aren't implemented on the CPU backend"
                    "aren't implemented",
                )
            ):
                pytest.skip(f"CPU collectives unsupported: {err[-200:]}")
            raise AssertionError(f"worker failed:\n{err[-2000:]}")
        outs.append(out)

    import re

    results = {}
    counts = {}
    for out in outs:
        # Regex rather than naive split: the worker's stdout can carry
        # interleaved runtime/log text on rare runs.
        for m in re.finditer(
            r"^RESULT (\d+) ([0-9.eE+-]+) (\d+)\s*$", out, re.MULTILINE
        ):
            results[int(m.group(1))] = float(m.group(2))
            counts[int(m.group(1))] = int(m.group(3))
    assert set(results) == {0, 1}
    assert counts[0] + counts[1] == 64
    # Both processes computed disjoint halves; the allreduced total must
    # agree across processes.
    assert results[0] == pytest.approx(results[1], rel=1e-10)
    # Parallel VTK: one piece per process plus the rank-0 PVTU index.
    import os
    assert (tmp_path / "flux_p0000.vtu").exists()
    assert (tmp_path / "flux_p0001.vtu").exists()
    index = (tmp_path / "flux.pvtu").read_text()
    assert "flux_p0000.vtu" in index and "flux_p0001.vtu" in index

    # And equal the single-process full-batch walk.
    import jax.numpy as jnp

    from pumiumtally_tpu import build_box, make_flux
    from pumiumtally_tpu.ops.walk import trace_impl

    mesh = build_box(1.0, 1.0, 1.0, 3, 3, 3, dtype=jnp.float64)
    rng = np.random.default_rng(0)
    N = 64
    elem = rng.integers(0, mesh.ntet, N).astype(np.int32)
    origin = np.asarray(mesh.centroids())[elem]
    dest = rng.uniform(0.02, 0.98, (N, 3))
    weight = rng.uniform(0.5, 2.0, N)
    r = trace_impl(
        mesh,
        jnp.asarray(origin, jnp.float64),
        jnp.asarray(dest, jnp.float64),
        jnp.asarray(elem),
        jnp.ones(N, bool),
        jnp.asarray(weight, jnp.float64),
        jnp.zeros(N, jnp.int32),
        jnp.full(N, -1, jnp.int32),
        make_flux(mesh.ntet, 1, jnp.float64),
        initial=False,
        max_crossings=mesh.ntet + 8,
        tolerance=1e-8,
    )
    expect = float(np.asarray(r.flux)[..., 0].sum())
    assert results[0] == pytest.approx(expect, rel=1e-10)


# --------------------------------------------------------------------------- #
# Per-function coverage (single-process, monkeypatched ranks) — so a failure
# localizes to the broken piece instead of one opaque red cluster test.
# --------------------------------------------------------------------------- #
class TestInitDistributed:
    def test_single_process_is_noop(self, monkeypatch):
        from pumiumtally_tpu.parallel import multihost

        monkeypatch.delenv("JAX_COORDINATOR_ADDRESS", raising=False)
        monkeypatch.delenv("JAX_NUM_PROCESSES", raising=False)
        assert multihost.init_distributed() is False
        # num_processes=1 is a no-op even with a coordinator configured.
        assert multihost.init_distributed("127.0.0.1:1", 1, 0) is False
        # No coordinator address → no-op regardless of process count.
        assert multihost.init_distributed(None, 4, 0) is False

    def test_idempotent_after_init(self, monkeypatch):
        from pumiumtally_tpu.parallel import multihost

        # Once the cluster is up, a second call must return True without
        # touching jax.distributed.initialize again (which would raise).
        monkeypatch.setattr(multihost, "_initialized", True)

        def boom(**kw):  # pragma: no cover - must not be reached
            raise AssertionError("re-initialized a live cluster")

        monkeypatch.setattr(
            multihost.jax.distributed, "initialize", boom
        )
        assert multihost.init_distributed("127.0.0.1:1", 2, 0) is True

    def test_env_var_contract(self, monkeypatch):
        from pumiumtally_tpu.parallel import multihost

        calls = {}

        def fake_init(coordinator_address, num_processes, process_id):
            calls.update(
                addr=coordinator_address, n=num_processes, pid=process_id
            )

        monkeypatch.setattr(multihost, "_initialized", False)
        monkeypatch.setattr(
            multihost.jax.distributed, "initialize", fake_init
        )
        monkeypatch.setenv("JAX_COORDINATOR_ADDRESS", "10.0.0.1:1234")
        monkeypatch.setenv("JAX_NUM_PROCESSES", "4")
        monkeypatch.setenv("JAX_PROCESS_ID", "3")
        assert multihost.init_distributed() is True
        assert calls == {"addr": "10.0.0.1:1234", "n": 4, "pid": 3}


class TestHostLocalBatch:
    @pytest.mark.parametrize(
        "size,n", [(1, 7), (2, 64), (3, 64), (3, 2), (4, 0), (8, 101)]
    )
    def test_split_covers_disjointly(self, monkeypatch, size, n):
        import jax

        from pumiumtally_tpu.parallel.multihost import host_local_batch

        monkeypatch.setattr(jax, "process_count", lambda: size)
        spans = []
        for rank in range(size):
            monkeypatch.setattr(jax, "process_index", lambda r=rank: r)
            start, count = host_local_batch(n)
            assert count >= 0
            spans.append((start, count))
        # Contiguous, ordered, disjoint, covering exactly [0, n), and
        # balanced to within one particle (the work_per_rank contract).
        pos = 0
        for start, count in spans:
            assert start == pos
            pos += count
        assert pos == n
        counts = [c for _, c in spans]
        assert max(counts) - min(counts) <= 1


class TestAllreduceFlux:
    def test_single_process_identity(self):
        from pumiumtally_tpu.parallel.multihost import allreduce_flux

        flux = np.arange(24, dtype=np.float64).reshape(2, 6, 2)
        for in_program in (True, False):
            out = allreduce_flux(flux, in_program=in_program)
            np.testing.assert_array_equal(out, flux)

    def test_in_program_failure_falls_back(self, monkeypatch):
        import jax

        from pumiumtally_tpu.parallel import multihost

        monkeypatch.setattr(jax, "process_count", lambda: 2)

        def broken(local):
            raise RuntimeError("no collectives here")

        gathered = {}

        def fake_allgather(x):
            gathered["called"] = True
            return np.stack([np.asarray(x), np.asarray(x)])

        monkeypatch.setattr(
            multihost, "_allreduce_flux_in_program", broken
        )
        from jax.experimental import multihost_utils

        monkeypatch.setattr(
            multihost_utils, "process_allgather", fake_allgather
        )
        flux = np.ones((3, 1, 2))
        out = multihost.allreduce_flux(flux, in_program=True)
        assert gathered.get("called"), "fallback path not taken"
        np.testing.assert_array_equal(out, 2 * flux)


class TestWriteParallelVtk:
    def test_piece_and_index_content(self, tmp_path, monkeypatch):
        import jax
        import jax.numpy as jnp

        from pumiumtally_tpu import build_box
        from pumiumtally_tpu.parallel.multihost import write_parallel_vtk

        mesh = build_box(1, 1, 1, 2, 2, 2, dtype=jnp.float64)
        flux = np.random.default_rng(0).random((mesh.ntet, 2, 2))
        monkeypatch.setattr(jax, "process_index", lambda: 0)
        monkeypatch.setattr(jax, "process_count", lambda: 3)
        piece = write_parallel_vtk(str(tmp_path / "out"), mesh, flux)
        assert piece == str(tmp_path / "out_p0000.vtu")
        body = (tmp_path / "out_p0000.vtu").read_text()
        assert "flux_group_0" in body and "flux_group_1" in body
        index = (tmp_path / "out.pvtu").read_text()
        # The rank-0 index must reference every process's piece by its
        # RELATIVE name (a .pvtu with absolute paths breaks on move).
        for r in range(3):
            assert f"out_p{r:04d}.vtu" in index
        assert str(tmp_path) not in index

    def test_nonzero_rank_writes_no_index(self, tmp_path, monkeypatch):
        import jax
        import jax.numpy as jnp

        from pumiumtally_tpu import build_box
        from pumiumtally_tpu.parallel.multihost import write_parallel_vtk

        mesh = build_box(1, 1, 1, 2, 2, 2, dtype=jnp.float64)
        flux = np.zeros((mesh.ntet, 1, 2))
        monkeypatch.setattr(jax, "process_index", lambda: 1)
        monkeypatch.setattr(jax, "process_count", lambda: 2)
        write_parallel_vtk(
            str(tmp_path / "out"), mesh, flux,
            elem_slice=slice(0, mesh.ntet // 2),
        )
        assert (tmp_path / "out_p0001.vtu").exists()
        assert not (tmp_path / "out.pvtu").exists()
        # elem_slice restricts the piece to this host's elements.
        body = (tmp_path / "out_p0001.vtu").read_text()
        assert f'NumberOfCells="{mesh.ntet // 2}"' in body


# --------------------------------------------------------------------------- #
# Two-process PARTITIONED walk: cross-chip particle migration where half the
# "chips" live in another OS process — the reference's production shape
# (MPI ranks each owning mesh parts). Exercises shard_map all_to_all
# migration + the halo guest-flux fold over the multi-process backend.
# --------------------------------------------------------------------------- #
WORKER_PARTITIONED = textwrap.dedent(
    """
    import sys
    import jax
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_enable_x64", True)

    coord, pid = sys.argv[1], int(sys.argv[2])
    from pumiumtally_tpu.parallel.multihost import init_distributed
    assert init_distributed(coord, 2, pid)
    import numpy as np
    import jax.numpy as jnp
    from jax.experimental import multihost_utils
    from jax.sharding import Mesh
    from pumiumtally_tpu import build_box, make_flux
    from pumiumtally_tpu.ops.walk import trace_impl
    from pumiumtally_tpu.ops.walk_partitioned import (
        distribute_particles, make_partitioned_step,
    )
    from pumiumtally_tpu.parallel.mesh_partition import (
        assemble_global_flux, partition_mesh,
    )
    from pumiumtally_tpu.parallel.particle_sharding import PARTICLE_AXIS

    n_dev = jax.device_count()
    assert n_dev == 8 and jax.local_device_count() == 4
    dmesh = Mesh(np.asarray(jax.devices()), (PARTICLE_AXIS,))

    # Same mesh/batch on every process (same seed) — each process only
    # touches its addressable shards.
    mesh = build_box(1.0, 1.0, 1.0, 4, 4, 4, dtype=jnp.float64)
    part = partition_mesh(mesh, n_dev, halo_layers=1)
    n = 64
    rng = np.random.default_rng(0)
    elem = rng.integers(0, mesh.ntet, n).astype(np.int32)
    origin = np.asarray(mesh.centroids())[elem]
    dest = np.clip(origin + rng.uniform(-0.6, 0.6, (n, 3)), -0.1, 1.1)
    weight = rng.uniform(0.5, 2.0, n)
    group = rng.integers(0, 2, n).astype(np.int32)

    step = make_partitioned_step(
        dmesh, part, n_groups=2, max_crossings=mesh.ntet + 8,
        tolerance=1e-8,
    )
    placed = distribute_particles(
        part, dmesh, elem,
        dict(origin=origin, dest=dest, weight=weight, group=group,
             material_id=np.full(n, -1, np.int32)),
    )
    from jax.sharding import NamedSharding, PartitionSpec as P
    flux = jax.device_put(
        jnp.zeros((n_dev, part.max_local, 2, 2), jnp.float64),
        NamedSharding(dmesh, P(PARTICLE_AXIS)),
    )
    res = step(
        placed["origin"], placed["dest"], placed["elem"],
        jnp.zeros_like(placed["valid"]), placed["material_id"],
        placed["weight"], placed["group"], placed["particle_id"],
        placed["valid"], flux,
    )
    # Globalize results host-side (process_allgather collects every
    # process's addressable shards).
    def ag(x):
        return np.asarray(
            multihost_utils.process_allgather(x, tiled=True)
        )
    slabs = ag(res.flux)
    valid = ag(res.valid)
    done = ag(res.done)
    dropped = int(ag(res.n_dropped).sum())
    nseg = int(ag(res.n_segments).sum())
    assert dropped == 0
    assert not (valid & ~done).any()
    g_flux = assemble_global_flux(part, slabs)

    # Local single-chip oracle (full mesh on every process).
    ref = trace_impl(
        mesh, jnp.asarray(origin), jnp.asarray(dest), jnp.asarray(elem),
        jnp.ones(n, bool), jnp.asarray(weight), jnp.asarray(group),
        jnp.full(n, -1, jnp.int32), make_flux(mesh.ntet, 2, jnp.float64),
        initial=False, max_crossings=mesh.ntet + 8, tolerance=1e-8,
    )
    assert int(ref.n_segments) == nseg, (int(ref.n_segments), nseg)
    assert np.allclose(g_flux, np.asarray(ref.flux), rtol=0, atol=1e-12)
    print("PRESULT", pid, nseg, int(ag(res.n_rounds)[0]))
    """
)


def test_two_process_partitioned_migration():
    """The partitioned walk's all_to_all migration + halo guest-flux fold
    must produce single-chip-exact results when the 8 mesh parts span two
    OS processes (4 virtual devices each) over the TCP backend."""
    port = _free_port()
    coord = f"127.0.0.1:{port}"
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", WORKER_PARTITIONED, coord, str(i)],
            stdout=subprocess.PIPE,
            stderr=subprocess.PIPE,
            text=True,
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            env=env,
        )
        for i in range(2)
    ]
    outs = []
    for p in procs:
        try:
            out, err = p.communicate(timeout=300)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            pytest.skip("distributed CPU cluster timed out")
        if p.returncode != 0:
            if any(
                key in err
                for key in (
                    "not implemented",
                    "UNIMPLEMENTED",
                    "Unsupported",
                    # jax 0.4.x CPU backend phrasing: "Multiprocess
                    # computations aren't implemented on the CPU backend"
                    "aren't implemented",
                )
            ):
                pytest.skip(f"CPU collectives unsupported: {err[-200:]}")
            raise AssertionError(f"worker failed:\n{err[-2000:]}")
        outs.append(out)
    import re

    seen = {}
    for out in outs:
        for m in re.finditer(
            r"^PRESULT (\d+) (\d+) (\d+)\s*$", out, re.MULTILINE
        ):
            seen[int(m.group(1))] = (int(m.group(2)), int(m.group(3)))
    assert set(seen) == {0, 1}
    # Both processes agreed on the global segment count (and the round
    # count is a replicated value).
    assert seen[0] == seen[1]
